"""Lock-free serving data plane (ISSUE 14): native frame reader + decode
pool vs the pure-Python oracle.

The contracts under test:

* DECODE EQUIVALENCE — the native one-pass ``decode_wire_into`` (and the
  pool built on it) is bit-identical to the ``validate_wire_buffer``
  numpy oracle on well-formed buffers of every push encoding (fixed
  widths, PAIR40, BDV), and raises the IDENTICAL typed refusal (message
  included) on garbage, truncated, oversized, negative-id, and
  boundary-varint buffers.
* FRAME EQUIVALENCE — the native GLY1 prefix probe and the Python parser
  produce identical outcomes (accept/``BadFrame``/``FrameTooLarge``,
  messages included) over fuzzed prefixes, and ``FrameReader``'s
  arena-reuse read path yields the same (header, payload) sequence as
  ``read_frame``.
* SERVER EQUIVALENCE — the same stream through ``decode_workers=0`` (the
  oracle) and a live pool produces bit-identical emission leaves, with
  refusals surviving the connection either way.
* SOAK — multiple clients over the pool with a non-idempotent fold:
  exact counts, 0 recompiles, arenas recycled.
"""

import io
import struct
import threading

import numpy as np
import pytest

from gelly_streaming_tpu.core.config import (
    RuntimeConfig,
    ServerConfig,
    StreamConfig,
)
from gelly_streaming_tpu.core.stream import validate_wire_buffer
from gelly_streaming_tpu.io import wire
from gelly_streaming_tpu.runtime import JobManager
from gelly_streaming_tpu.runtime import protocol
from gelly_streaming_tpu.runtime.client import GellyClient, ServerRefused
from gelly_streaming_tpu.runtime.decode_pool import (
    DecodePool,
    resolve_decode_workers,
)
from gelly_streaming_tpu.runtime.server import StreamServer, record_leaves
from gelly_streaming_tpu.utils.native import load_ingest_lib

pytestmark = pytest.mark.timeout_cap(300)

HAVE_NATIVE = (
    load_ingest_lib() is not None
    and hasattr(load_ingest_lib(), "decode_wire_into")
)

CAP = 1 << 12
W = 1 << 10
B = 1 << 9
N = 4 * W


def _graph(seed, n=N, cap=CAP):
    rng = np.random.default_rng(seed)
    return (
        rng.integers(0, cap, n).astype(np.int32),
        rng.integers(0, cap, n).astype(np.int32),
    )


def _oracle(buf, n, width, capacity, sort=False):
    """(src, dst) or the raised ValueError, from the pure-Python path."""
    try:
        return wire.decode_wire_np(buf, n, width, capacity, sort=sort), None
    except ValueError as e:
        return None, e


def _native(buf, n, width, capacity, sort=False):
    """(src, dst) or the raised ValueError, via decode_wire_into."""
    out_s = np.empty(n, np.int32)
    out_d = np.empty(n, np.int32)
    try:
        ran = wire.decode_wire_into(
            buf, n, width, capacity, out_s, out_d, sort=sort
        )
    except ValueError as e:
        return None, e
    if not ran:
        return None, "unavailable"
    return (out_s, out_d), None


# ---------------------------------------------------------------------------
# decode equivalence: well-formed buffers, every encoding
# ---------------------------------------------------------------------------


@pytest.mark.skipif(not HAVE_NATIVE, reason="no native toolchain")
@pytest.mark.parametrize(
    "cap,width",
    [
        (1 << 12, 2),
        (1 << 16, 2),
        (1 << 18, wire.PAIR40),
        (1 << 22, 3),
        (1 << 26, 4),
        (1 << 12, (wire.BDV, 1 << 12)),
        (1 << 20, (wire.BDV, 1 << 20)),
    ],
)
def test_native_decode_bit_identical_on_valid_buffers(cap, width):
    rng = np.random.default_rng(hash(str(width)) % (1 << 32))
    for n in (1, 7, 256, 1024):
        s = rng.integers(0, cap, n).astype(np.int32)
        d = rng.integers(0, cap, n).astype(np.int32)
        buf = wire.pack_edges(s, d, width)
        for sort in (False, True):
            got, err = _native(buf, n, width, cap, sort=sort)
            assert err is None, err
            want, werr = _oracle(buf, n, width, cap, sort=sort)
            assert werr is None
            assert np.array_equal(got[0], want[0])
            assert np.array_equal(got[1], want[1])


@pytest.mark.skipif(not HAVE_NATIVE, reason="no native toolchain")
def test_native_decode_boundary_varints_and_id_extremes():
    """BDV deltas at every varint length boundary (1/2/3/4 bytes) and ids
    at both ends of the range decode identically."""
    cap = 1 << 20
    width = (wire.BDV, cap)
    # dst deltas straddling the 1/2/3-byte varint boundaries; src jumping
    # max-negative/max-positive zigzag swings, ids touching 0 and cap-1
    s = np.array([cap - 1, 0, cap - 1, 0, 1, cap - 1, 0, 2], np.int32)
    d = np.array([0, 0xFF, 0x100, 0xFFFF, 0x10000, 0x10000, 0x1FFFF,
                  cap - 1], np.int32)
    order = np.lexsort((s, d))
    s, d = s[order], d[order]
    buf = wire.pack_edges_bdv(s, d, cap, sort=False)
    n = len(s)
    got, err = _native(buf, n, width, cap)
    assert err is None
    want, _ = _oracle(buf, n, width, cap)
    assert np.array_equal(got[0], want[0])
    assert np.array_equal(got[1], want[1])


# ---------------------------------------------------------------------------
# decode equivalence: refusals (identical typed error, identical message)
# ---------------------------------------------------------------------------


def _refusal_cases():
    """(label, buf, n, width, capacity) malformed-buffer corpus."""
    rng = np.random.default_rng(99)
    cases = []
    s, d = _graph(5, 64, CAP)
    fixed = wire.pack_edges(s, d, 2)
    # garbage bytes of the right size still decode — ids out of range
    junk = rng.integers(0, 256, fixed.nbytes).astype(np.uint8)
    cases.append(("garbage-right-size", junk, 64, 2, 100))
    # truncated / oversized fixed buffers
    cases.append(("fixed-truncated", fixed[:-3], 64, 2, CAP))
    cases.append(
        ("fixed-oversized", np.append(fixed, fixed[:5]), 64, 2, CAP)
    )
    # out-of-range ids (width can express past capacity)
    big = np.full(64, CAP + 7, np.int32)
    cases.append(("ids-past-cap", wire.pack_edges(big, big, 2), 64, 2, CAP))
    # pair40 wrong size
    p40 = wire.pack_edges(s, d, wire.PAIR40)
    cases.append(("pair40-truncated", p40[:-1], 64, wire.PAIR40, CAP))
    # BDV: below floor, above worst-case bound, declared-length truncation
    bdv = wire.pack_edges_bdv(s, d, CAP)
    cases.append(("bdv-below-floor", bdv[:16], 64, (wire.BDV, CAP), CAP))
    cases.append(
        (
            "bdv-above-bound",
            np.zeros(wire.bdv_max_nbytes(64) + 1, np.uint8),
            64,
            (wire.BDV, CAP),
            CAP,
        )
    )
    # control block declaring 4-byte varints the payload doesn't hold:
    # all-0xFF control = every varint 4 bytes -> needed >> nbytes
    torn = np.full(wire.bdv_max_nbytes(64) - 8, 0xFF, np.uint8)
    cases.append(("bdv-declared-truncation", torn, 64, (wire.BDV, CAP), CAP))
    # negative ids: a zigzag src delta that sums negative
    sn = np.array([-5, 3], np.int32)
    dn = np.array([1, 2], np.int32)
    neg = wire._encode_bdv_np(sn, dn)
    cases.append(("bdv-negative-src", neg, 2, (wire.BDV, CAP), CAP))
    # fuzzed random BDV buffers across the legal size window (most refuse
    # on truncation or range; any accepted ones must match bit-for-bit)
    for k in range(12):
        nb = int(
            rng.integers(
                (2 * 32 + 3) // 4 + 2 * 32, wire.bdv_max_nbytes(32) + 1
            )
        )
        fuzz = rng.integers(0, 256, nb).astype(np.uint8)
        cases.append((f"bdv-fuzz-{k}", fuzz, 32, (wire.BDV, CAP), CAP))
    return cases


@pytest.mark.skipif(not HAVE_NATIVE, reason="no native toolchain")
@pytest.mark.parametrize(
    "label,buf,n,width,cap",
    _refusal_cases(),
    ids=[c[0] for c in _refusal_cases()],
)
def test_native_refusals_identical_to_oracle(label, buf, n, width, cap):
    got, gerr = _native(buf, n, width, cap)
    want, werr = _oracle(buf, n, width, cap)
    assert gerr != "unavailable"
    if werr is None:
        assert gerr is None, f"{label}: native refused, oracle accepted"
        assert np.array_equal(got[0], want[0])
        assert np.array_equal(got[1], want[1])
    else:
        assert gerr is not None, f"{label}: native accepted, oracle refused"
        assert str(gerr) == str(werr), label


def test_pool_raises_oracle_refusals():
    """Through the POOL (worker thread round trip), a refused buffer
    raises the oracle's exact message and releases its arena."""
    with DecodePool(2) as pool:
        bad = np.zeros(7, np.uint8)
        _want, werr = _oracle(bad, B, 2, CAP)
        with pytest.raises(ValueError) as e:
            pool.decode(bad, 2, B, CAP)
        assert str(e.value) == str(werr)
        # a good buffer still decodes after the refusal, on a recycled
        # arena (free-list round trip)
        s, d = _graph(11, B, CAP)
        buf = wire.pack_edges(s, d, 2)
        out_s, out_d, release = pool.decode(buf, 2, B, CAP)
        assert np.array_equal(out_s, s) and np.array_equal(out_d, d)
        release()


# ---------------------------------------------------------------------------
# frame-prefix probe + FrameReader equivalence
# ---------------------------------------------------------------------------


def _prefix_outcome(prefix, max_payload, native):
    try:
        return protocol.parse_prefix(prefix, max_payload, native=native), None
    except (protocol.BadFrame, protocol.FrameTooLarge) as e:
        return None, (type(e).__name__, str(e))


@pytest.mark.skipif(
    protocol._native_probe() is None, reason="no native toolchain"
)
def test_frame_prefix_probe_matches_python_parser():
    rng = np.random.default_rng(23)
    cases = [
        struct.pack(">4sII", b"GLY1", 10, 20),
        struct.pack(">4sII", b"GLY1", protocol.MAX_HEADER_BYTES + 1, 0),
        struct.pack(">4sII", b"GLY1", 0, 1 << 30),
        struct.pack(">4sII", b"NOPE", 3, 4),
        b"GLY1" + b"\xff" * 8,  # giant lengths
        b"\x00" * 12,
    ] + [bytes(rng.integers(0, 256, 12, dtype=np.uint8)) for _ in range(64)]
    for prefix in cases:
        got = _prefix_outcome(prefix, 1 << 20, native=True)
        want = _prefix_outcome(prefix, 1 << 20, native=False)
        assert got == want, prefix.hex()


def test_frame_reader_matches_read_frame_over_pipelined_frames():
    frames = [
        ({"verb": "ping", "k": i}, bytes([i] * (i * 37 % 2048)))
        for i in range(16)
    ]
    blob = io.BytesIO()
    for head, pay in frames:
        protocol.write_frame(blob, head, pay)
    # read_frame (allocating) path
    blob.seek(0)
    want = []
    while True:
        frame = protocol.read_frame(blob)
        if frame is None:
            break
        want.append(frame)
    # FrameReader (arena-reuse) path; payloads must be copied per read —
    # the arena's documented validity window
    blob.seek(0)
    reader = protocol.FrameReader(blob)
    got = []
    while True:
        frame = reader.read()
        if frame is None:
            break
        head, view = frame
        got.append((head, bytes(view)))
    assert got == want


def test_frame_reader_typed_failures_match():
    # truncated mid-prefix
    reader = protocol.FrameReader(io.BytesIO(protocol.MAGIC + b"\x00"))
    with pytest.raises(protocol.BadFrame, match="mid-frame"):
        reader.read()
    # oversized declared payload, bytes unread
    blob = io.BytesIO(struct.pack(">4sII", b"GLY1", 0, 1 << 20))
    reader = protocol.FrameReader(blob, max_payload=1 << 10)
    with pytest.raises(protocol.FrameTooLarge, match="frame cap"):
        reader.read()
    # clean EOF at a boundary
    assert protocol.FrameReader(io.BytesIO(b"")).read() is None


# ---------------------------------------------------------------------------
# server-level equivalence + survival
# ---------------------------------------------------------------------------


def _run_server_stream(workers, seed=31, bdv=True, query="cc"):
    s, d = _graph(seed)
    leaves = []
    with JobManager(RuntimeConfig()) as jm, StreamServer(
        jm, ServerConfig(decode_workers=workers)
    ) as server:
        with GellyClient("127.0.0.1", server.port) as c:
            c.submit(
                name="eq", query=query, capacity=CAP, window_edges=W, batch=B
            )
            c.push_edges("eq", s, d, batch=B, capacity=CAP, bdv=bdv)
            for rec in c.iter_results("eq", deadline_s=240):
                leaves.append([np.asarray(x) for x in rec])
            status = c.status()["server"]
    return leaves, status


def test_pool_vs_python_oracle_bit_identical_server_run():
    """The acceptance oracle: GELLY_DECODE_WORKERS=0 (pure Python) and a
    live pool produce bit-identical emissions for the same stream."""
    want, st0 = _run_server_stream(0)
    got, st2 = _run_server_stream(2)
    assert st0["decode_workers"] == 0 and st0["decode"] is None
    assert st2["decode_workers"] == 2
    if HAVE_NATIVE:
        assert st2["decode"]["native"] > 0
        assert st2["decode"]["fallback"] == 0
    assert len(want) == len(got) and len(want) == N // W
    for a, b in zip(want, got):
        assert len(a) == len(b)
        for x, y in zip(a, b):
            assert np.array_equal(x, y)


def test_pool_refusals_survive_connection_and_match_python_path():
    """Same malformed pushes against a pooled and an oracle server: same
    refusal code AND message, connection alive afterwards."""
    s_ok, d_ok = _graph(7)

    def collect(workers):
        rows = []
        with JobManager(RuntimeConfig()) as jm, StreamServer(
            jm, ServerConfig(decode_workers=workers)
        ) as server:
            with GellyClient("127.0.0.1", server.port) as c:
                c.submit(
                    name="j", query="cc", capacity=CAP, window_edges=W,
                    batch=B,
                )
                bad = [
                    ("wire", np.zeros(7, np.uint8)),
                    ("wire", np.full(2 * B * 2, 0xFF, np.uint8)),
                    ("bdv", np.zeros(16, np.uint8)),
                    (
                        "bdv",
                        np.full(wire.bdv_max_nbytes(B) - 8, 0xFF, np.uint8),
                    ),
                ]
                for kind, buf in bad:
                    with pytest.raises(ServerRefused) as e:
                        c.push_wire("j", buf, kind=kind)
                    rows.append((e.value.code, str(e.value)))
                # the connection survived every refusal: stream the job out
                c.push_edges("j", s_ok, d_ok, batch=B, capacity=CAP)
                n_recs = len(list(c.iter_results("j", deadline_s=240)))
        return rows, n_recs

    want, n0 = collect(0)
    got, n2 = collect(2)
    assert want == got
    assert n0 == n2 == N // W
    assert all(code == "bad-wire" for code, _m in want)


def test_quiesced_refusal_precedes_decode_on_pooled_path():
    """A draining source refuses ``quiesced`` — not ``bad-wire`` — even
    for a malformed buffer, matching push_wire's guard order."""
    from gelly_streaming_tpu.io.sources import NetworkEdgeSource

    cfg = StreamConfig(
        vertex_capacity=CAP, batch_size=B, ingest_window_edges=W
    )
    src = NetworkEdgeSource(cfg, B)
    src.quiesce()
    with pytest.raises(Exception, match="draining"):
        src.check_open()


# ---------------------------------------------------------------------------
# soak: multi-client, non-idempotent counts, 0 recompiles, arena recycling
# ---------------------------------------------------------------------------


@pytest.mark.timeout_cap(600)
def test_multi_client_soak_exact_counts_zero_recompiles():
    from gelly_streaming_tpu.core import compile_cache

    clients = 4
    datasets = [_graph(100 + i) for i in range(clients)]
    # warm the executables so the soak run itself must compile nothing
    _run_server_stream(2, seed=100, bdv=False, query="edges")
    compile_cache.reset_stats()

    errors = []
    counts = {}
    with JobManager(RuntimeConfig(max_jobs=8)) as jm, StreamServer(
        jm, ServerConfig(decode_workers=2)
    ) as server:

        def run(i):
            try:
                s, d = datasets[i]
                with GellyClient("127.0.0.1", server.port) as c:
                    c.submit(
                        name=f"soak-{i}",
                        query="edges",
                        capacity=CAP,
                        window_edges=W,
                        batch=B,
                    )
                    c.push_edges(
                        f"soak-{i}", s, d, batch=B, capacity=CAP, bdv=True
                    )
                    vals = [
                        int(np.asarray(rec[0]))
                        for rec in c.iter_results(f"soak-{i}", deadline_s=240)
                    ]
                    counts[i] = vals
            except BaseException as e:  # surfaced below
                errors.append(e)

        threads = [
            threading.Thread(target=run, args=(i,)) for i in range(clients)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        pool = server._decode_pool
        stats = pool.stats()
        free_arenas = sum(len(v) for v in pool._arenas._free.values())
    if errors:
        raise errors[0]
    # exact non-idempotent counts: the running edge-count fold saw every
    # window exactly once, per client
    serial = [(k + 1) * W for k in range(N // W)]
    for i in range(clients):
        assert counts[i] == serial, f"client {i}: {counts[i]}"
    assert compile_cache.stats()["recompiles"] == 0
    assert compile_cache.stats()["compiles"] == 0
    # every pushed batch went through the pool, and the arenas came back
    assert stats["native" if HAVE_NATIVE else "fallback"] >= clients * (
        N // B
    )
    assert free_arenas > 0  # recycling actually happened


def test_resolve_decode_workers_contract(monkeypatch):
    monkeypatch.delenv("GELLY_DECODE_WORKERS", raising=False)
    assert resolve_decode_workers(0) == 0
    assert resolve_decode_workers(3) == 3
    from gelly_streaming_tpu.runtime.decode_pool import DEFAULT_DECODE_WORKERS

    assert resolve_decode_workers(-1) == DEFAULT_DECODE_WORKERS
    monkeypatch.setenv("GELLY_DECODE_WORKERS", "5")
    assert resolve_decode_workers(-1) == 5
    assert resolve_decode_workers(1) == 1  # config beats env
    monkeypatch.setenv("GELLY_DECODE_WORKERS", "lots")
    with pytest.raises(ValueError, match="GELLY_DECODE_WORKERS"):
        resolve_decode_workers(-1)


def test_decoded_batches_copy_out_before_arena_release():
    """The donation fence: after the factory yields a batch, mutating the
    (recycled) arena must not change the batch the consumer holds."""
    from gelly_streaming_tpu.core.types import EdgeBatch
    from gelly_streaming_tpu.io.sources import NetworkEdgeSource

    cfg = StreamConfig(vertex_capacity=CAP, batch_size=B)
    src = NetworkEdgeSource(cfg, B)
    arena = np.zeros((2, B), np.int32)
    arena[0, :] = np.arange(B)
    arena[1, :] = np.arange(B) + 1
    fired = []
    src.push_decoded(
        arena[0], arena[1], release=lambda: fired.append(True)
    )
    src.close()
    batches = list(src._factory())
    assert len(batches) == 1 and fired == [True]
    arena[:] = -1  # "recycled" by a later decode
    assert np.array_equal(np.asarray(batches[0].src), np.arange(B))
    assert np.array_equal(np.asarray(batches[0].dst), np.arange(B) + 1)

"""MeshAggregationRunner: sharded window fold+combine on the 8-device mesh.

The single-device runtime simulates partitions sequentially; the mesh runner
executes the same descriptor as one shard_map step (per-shard fold,
all_gather of partials over the mesh axis, combine fold).  Both must agree —
the summaries' combines are associative/commutative by construction — so
these tests compare the mesh runner's emissions against the simulated
runtime's on the 8-device CPU mesh (the MiniCluster analog).
"""

import numpy as np
import pytest

from gelly_streaming_tpu.core.aggregation import MeshAggregationRunner
from gelly_streaming_tpu.core.config import StreamConfig
from gelly_streaming_tpu.core.stream import EdgeStream
from gelly_streaming_tpu.library.bipartiteness import BipartitenessCheck
from gelly_streaming_tpu.library.connected_components import (
    ConnectedComponents,
    ConnectedComponentsTree,
)


def _cfg():
    return StreamConfig(vertex_capacity=64, batch_size=4, window_ms=1000)


def _cc_edges():
    # two components {1..4}, {5..8}, streamed over several windows
    return [
        (1, 2, 0.0, 10),
        (3, 4, 0.0, 20),
        (5, 6, 0.0, 1010),
        (2, 3, 0.0, 1020),
        (7, 8, 0.0, 2010),
        (6, 7, 0.0, 2020),
    ]


@pytest.mark.parametrize("agg_cls", [ConnectedComponents, ConnectedComponentsTree])
def test_mesh_cc_matches_simulated_runtime(agg_cls):
    stream = lambda: EdgeStream.from_collection(  # noqa: E731
        _cc_edges(), _cfg(), batch_size=2, with_time=True
    )
    agg = agg_cls()
    expected = [str(s[0]) for s in agg.run(stream())]
    runner = MeshAggregationRunner(agg)
    assert runner.num_shards == 8
    got = [str(s[0]) for s in runner.run(stream())]
    assert got == expected
    # final window: both components fully merged
    assert "1 2 3 4" in got[-1].replace(",", " ").replace("[", " ").replace(
        "]", " "
    ) or "[1, 2, 3, 4]" in got[-1]


def test_mesh_bipartiteness_detects_odd_cycle():
    cfg = _cfg()
    bip_edges = [(1, 2, 0.0, 10), (2, 3, 0.0, 20), (3, 4, 0.0, 1010), (4, 1, 0.0, 1020)]
    odd_edges = bip_edges + [(1, 3, 0.0, 2010)]

    for edges, expect_ok in [(bip_edges, True), (odd_edges, False)]:
        stream = EdgeStream.from_collection(edges, cfg, batch_size=2, with_time=True)
        runner = MeshAggregationRunner(BipartitenessCheck())
        outs = list(runner.run(stream))
        final = outs[-1][0]
        assert final.is_bipartite() == expect_ok
        # mesh emissions match the simulated runtime
        stream2 = EdgeStream.from_collection(edges, cfg, batch_size=2, with_time=True)
        expected = [str(o[0]) for o in BipartitenessCheck().run(stream2)]
        assert [str(o[0]) for o in outs] == expected


def test_mesh_runner_threads_edge_values():
    """Aggregations that fold edge values get them sharded alongside ids."""
    import jax.numpy as jnp

    from gelly_streaming_tpu.core.aggregation import SummaryBulkAggregation

    class WeightSum(SummaryBulkAggregation):
        def initial_state(self, cfg):
            return jnp.zeros((), jnp.float32)

        def update(self, state, src, dst, val, mask):
            return state + jnp.sum(jnp.where(mask, val, 0.0))

        def combine(self, a, b):
            return a + b

        def transform(self, state):
            return float(state)

    edges = [(i, i + 1, float(i), 10 + i) for i in range(11)]
    stream = EdgeStream.from_collection(edges, _cfg(), batch_size=3, with_time=True)
    outs = list(MeshAggregationRunner(WeightSum()).run(stream))
    assert outs == [(sum(range(11)),)]


def test_mesh_runner_handles_more_shards_than_edges():
    """Panes smaller than the shard count pad out with empty buckets."""
    cfg = _cfg()
    stream = EdgeStream.from_collection(
        [(1, 2, 0.0, 10)], cfg, batch_size=1, with_time=True
    )
    outs = list(MeshAggregationRunner(ConnectedComponents()).run(stream))
    assert len(outs) == 1
    assert "1" in str(outs[0][0]) and "2" in str(outs[0][0])


def test_mesh_excludes_empty_shards_from_combine():
    """Empty shards must not feed initial_state into the combine — descriptors
    whose initial state is not a combine identity would diverge from the
    simulated runtime (which skips empty partitions)."""
    import jax.numpy as jnp

    from gelly_streaming_tpu.core.aggregation import SummaryBulkAggregation

    class PartialCount(SummaryBulkAggregation):
        """Summary = how many non-empty partials were combined."""

        def initial_state(self, cfg):
            return jnp.ones((), jnp.int32)

        def update(self, state, src, dst, val, mask):
            return state

        def combine(self, a, b):
            return a + b

        def transform(self, state):
            return int(state)

    cfg = _cfg()
    # 3 edges over 8 shards: exactly 3 non-empty buckets
    stream = EdgeStream.from_collection(
        [(1, 2, 0.0, 10), (3, 4, 0.0, 11), (5, 6, 0.0, 12)],
        cfg,
        batch_size=3,
        with_time=True,
    )
    outs = list(MeshAggregationRunner(PartialCount()).run(stream))
    assert outs == [(3,)]


def test_tree_degree_4_matches_flat_on_mesh():
    """cfg.tree_degree / explicit degree feed the k-ary combine rounds
    (SummaryTreeReduce.java:53-75); any fan-in reaches the same fixed point."""
    stream = lambda: EdgeStream.from_collection(  # noqa: E731
        _cc_edges(), _cfg(), batch_size=2, with_time=True
    )
    flat = [str(s[0]) for s in ConnectedComponents().run(stream())]
    tree4 = ConnectedComponentsTree()
    tree4.degree = 4
    runner = MeshAggregationRunner(tree4)
    got = [str(s[0]) for s in runner.run(stream())]
    assert got == flat
    # the k-ary fold itself: 7 items at fan-in 4 -> rounds of [4,3] then [2]
    calls = []
    tree = ConnectedComponentsTree()
    acc = tree._fold_partials(
        list(range(7)), lambda a, b: calls.append((a, b)) or b, fanin=4
    )
    assert acc == 6 and len(calls) == 6  # 6 combines for 7 partials


def test_mesh_runner_kill_and_resume(tmp_path):
    """Positional checkpoints on the sharded data plane: a killed run resumes
    from the last closed window without refolding it (VERDICT r1 item 4)."""
    import os

    cfg = _cfg()
    ckpt = os.path.join(str(tmp_path), "mesh_cc.npz")
    stream = lambda: EdgeStream.from_collection(  # noqa: E731
        _cc_edges(), cfg, batch_size=2, with_time=True
    )
    runner = MeshAggregationRunner(ConnectedComponents())

    # "crash" after consuming two windows (generator abandoned mid-stream)
    it = iter(runner.run(stream(), checkpoint_path=ckpt))
    first_two = [next(it), next(it)]
    it.close()
    assert os.path.exists(ckpt)

    # resume: the full stream replays; windows snapshot before the crash are
    # skipped.  The second emission's snapshot never ran (the generator was
    # killed suspended at the yield), so its window re-emits — the documented
    # at-least-once emission semantics of the Merger.
    resumed = list(
        MeshAggregationRunner(ConnectedComponents()).run(
            stream(), checkpoint_path=ckpt
        )
    )
    full = [
        str(r[0]) for r in MeshAggregationRunner(ConnectedComponents()).run(stream())
    ]
    assert [str(r[0]) for r in resumed] == full[1:]
    assert str(resumed[-1][0]) == full[-1]
    assert [str(r[0]) for r in first_two] == full[:2]


def test_aggregate_routes_to_mesh_when_sharded():
    """cfg.num_shards > 1 + enough devices -> EdgeStream.aggregate runs the
    sharded data plane (unification, VERDICT r1 item 4)."""
    cfg = StreamConfig(
        vertex_capacity=64, batch_size=4, window_ms=1000, num_shards=8
    )
    stream = EdgeStream.from_collection(_cc_edges(), cfg, 2, with_time=True)
    agg = ConnectedComponents()
    outs = [str(o[0]) for o in stream.aggregate(agg)]
    assert agg._mesh_runner_cache is not None
    assert agg._mesh_runner_cache.num_shards == 8
    base_cfg = _cfg()
    stream2 = EdgeStream.from_collection(_cc_edges(), base_cfg, 2, with_time=True)
    assert outs == [str(o[0]) for o in ConnectedComponents().run(stream2)]


def test_mesh_runner_rides_packed_wire_ingest(monkeypatch):
    """TIMED value-less panes must ship as packed wire rows (not raw int32
    buckets), through the pane prefetcher (VERDICT r2 missing #3)."""
    import gelly_streaming_tpu.core.aggregation as agg_mod
    from gelly_streaming_tpu.core.stream import EdgeStream
    from gelly_streaming_tpu.core.types import EdgeBatch
    from gelly_streaming_tpu.library.connected_components import ConnectedComponents

    rng = np.random.default_rng(9)
    src = rng.integers(0, 64, 512).astype(np.int32)
    dst = rng.integers(0, 64, 512).astype(np.int32)
    times = np.sort(rng.integers(0, 3000, 512)).astype(np.int64)
    cfg = StreamConfig(
        vertex_capacity=64, batch_size=64, num_shards=8, window_ms=1000
    )

    def batches():
        for i in range(0, 512, 64):
            yield EdgeBatch.from_arrays(
                src[i : i + 64], dst[i : i + 64], time=times[i : i + 64]
            )

    agg = ConnectedComponents()
    calls = {"wire": 0, "raw": 0, "sharded_wire": 0, "sharded_raw": 0}
    orig_wire = agg_mod.MeshAggregationRunner._pane_step_wire
    orig_raw = agg_mod.MeshAggregationRunner._pane_step
    orig_sharded = agg_mod.MeshAggregationRunner._pane_step_sharded

    def spy_wire(self, *a, **k):
        calls["wire"] += 1
        return orig_wire(self, *a, **k)

    def spy_raw(self, *a, **k):
        calls["raw"] += 1
        return orig_raw(self, *a, **k)

    def spy_sharded(self, cfg2, spec, cap, kind, ctx):
        calls["sharded_" + kind[0]] += 1
        return orig_sharded(self, cfg2, spec, cap, kind, ctx)

    monkeypatch.setattr(agg_mod.MeshAggregationRunner, "_pane_step_wire", spy_wire)
    monkeypatch.setattr(agg_mod.MeshAggregationRunner, "_pane_step", spy_raw)
    monkeypatch.setattr(
        agg_mod.MeshAggregationRunner, "_pane_step_sharded", spy_sharded
    )
    out = EdgeStream.from_batches(batches, cfg).aggregate(agg).collect()
    # the default (owner-sharded) plane still ships packed wire rows, and
    # nothing falls back to raw int32 buckets
    assert calls["sharded_wire"] > 0 and calls["sharded_raw"] == 0
    assert calls["raw"] == 0
    # the replicated oracle plane keeps its packed-wire ingest too
    import dataclasses

    out_rep = (
        EdgeStream.from_batches(
            batches, dataclasses.replace(cfg, sharded_state=0)
        )
        .aggregate(ConnectedComponents())
        .collect()
    )
    assert calls["wire"] > 0 and calls["raw"] == 0
    assert out_rep[-1][0].components() == out[-1][0].components()
    # and the final summary matches the single-shard runtime over one stream
    single_cfg = StreamConfig(vertex_capacity=64, batch_size=64, window_ms=1000)
    single = (
        EdgeStream.from_batches(batches, single_cfg)
        .aggregate(ConnectedComponents())
        .collect()
    )
    assert out[-1][0].components() == single[-1][0].components()


def test_mesh_wire_streaming_fold_replaces_pane_refold(monkeypatch):
    """UNTIMED wire-backed sharded streams fold ONCE per micro-batch group
    through the sharded streaming wire fold — per-shard donated carries, a
    single collective merge at stream end — instead of re-folding per pane
    (VERDICT r3 weak #3).  Covers both from_arrays and from_wire sources,
    with and without a tail remainder."""
    import gelly_streaming_tpu.core.aggregation as agg_mod
    from gelly_streaming_tpu.core.stream import EdgeStream
    from gelly_streaming_tpu.io import wire
    from gelly_streaming_tpu.library.connected_components import ConnectedComponents

    rng = np.random.default_rng(9)
    src = rng.integers(0, 64, 500).astype(np.int32)
    dst = rng.integers(0, 64, 500).astype(np.int32)
    cfg = StreamConfig(vertex_capacity=64, batch_size=64, num_shards=8)
    calls = {"stream": 0, "pane": 0}
    orig_stream = agg_mod.MeshAggregationRunner.wire_records
    orig_pane_wire = agg_mod.MeshAggregationRunner._pane_step_wire

    def spy_stream(self, *a, **k):
        calls["stream"] += 1
        return orig_stream(self, *a, **k)

    def spy_pane(self, *a, **k):
        calls["pane"] += 1
        return orig_pane_wire(self, *a, **k)

    monkeypatch.setattr(agg_mod.MeshAggregationRunner, "wire_records", spy_stream)
    monkeypatch.setattr(agg_mod.MeshAggregationRunner, "_pane_step_wire", spy_pane)

    single = (
        EdgeStream.from_arrays(
            src, dst, StreamConfig(vertex_capacity=64, batch_size=64)
        )
        .aggregate(ConnectedComponents())
        .collect()
    )

    out = EdgeStream.from_arrays(src, dst, cfg).aggregate(
        ConnectedComponents()
    ).collect()
    assert calls["stream"] > 0 and calls["pane"] == 0
    assert out[-1][0].components() == single[-1][0].components()

    # replay source: 7 full buffers + a 52-edge tail over 8 shards
    width = wire.width_for_capacity(64)
    bufs, tail = wire.pack_stream(src, dst, 64, width)
    assert tail is not None
    out2 = (
        EdgeStream.from_wire(bufs, 64, width, cfg, tail=tail)
        .aggregate(ConnectedComponents())
        .collect()
    )
    assert out2[-1][0].components() == single[-1][0].components()


def test_mesh_wire_streaming_fold_kill_and_resume(tmp_path):
    """Positional checkpoints on the sharded streaming wire fold: a killed
    run resumes from the snapshot position and reaches the same summary."""
    import os

    from gelly_streaming_tpu.core.stream import EdgeStream
    from gelly_streaming_tpu.library.connected_components import ConnectedComponents

    rng = np.random.default_rng(21)
    src = rng.integers(0, 64, 512).astype(np.int32)
    dst = rng.integers(0, 64, 512).astype(np.int32)
    cfg = StreamConfig(
        vertex_capacity=64, batch_size=64, num_shards=8,
        wire_checkpoint_batches=8,
    )
    ckpt = os.path.join(str(tmp_path), "mesh_wire.npz")
    stream = lambda: EdgeStream.from_arrays(src, dst, cfg)  # noqa: E731

    # run to completion once WITH checkpointing: final snapshot marks done
    full = stream().aggregate(
        ConnectedComponents(), checkpoint_path=ckpt
    ).collect()
    assert os.path.exists(ckpt)
    # resume over the done snapshot: re-emits the same summary (at-least-once)
    resumed = stream().aggregate(
        ConnectedComponents(), checkpoint_path=ckpt
    ).collect()
    assert resumed[-1][0].components() == full[-1][0].components()

    # a mid-stream snapshot resumes without refolding earlier groups: corrupt
    # the source's earlier batches after the snapshot exists, then resume —
    # matching final components prove the restored carry was used
    os.remove(ckpt)
    it = iter(
        stream().aggregate(ConnectedComponents(), checkpoint_path=ckpt)
    )
    try:
        next(it)
    except StopIteration:
        pass
    it.close()
    assert os.path.exists(ckpt)  # at least one mid-stream snapshot landed
    garbled = src.copy()
    garbled[:256] = 0  # poison the already-folded prefix
    resumed2 = (
        EdgeStream.from_arrays(garbled, dst, cfg)
        .aggregate(ConnectedComponents(), checkpoint_path=ckpt)
        .collect()
    )
    assert resumed2[-1][0].components() == full[-1][0].components()


def test_mesh_runner_honors_ef40_encoding():
    rng = np.random.default_rng(11)
    src = rng.integers(0, 64, 400).astype(np.int32)
    dst = rng.integers(0, 64, 400).astype(np.int32)
    plain = (
        EdgeStream.from_arrays(
            src,
            dst,
            StreamConfig(
                vertex_capacity=64, batch_size=64, num_shards=8,
                wire_encoding="plain",
            ),
        )
        .aggregate(ConnectedComponents())
        .collect()
    )
    ef = (
        EdgeStream.from_arrays(
            src,
            dst,
            StreamConfig(
                vertex_capacity=64, batch_size=64, num_shards=8,
                wire_encoding="ef40",
            ),
        )
        .aggregate(ConnectedComponents())
        .collect()
    )
    assert plain[-1][0].components() == ef[-1][0].components()


def test_mesh_wire_ingest_volume_within_bound():
    """The sharded plane's transfer volume per pane stays within ~1.5x of the
    single-device wire path for pow2-friendly panes (VERDICT r2 item 3's
    per-shard ingest parity, stated in bytes — the deterministic invariant
    behind the timing claim)."""
    from gelly_streaming_tpu.core.aggregation import MeshAggregationRunner
    from gelly_streaming_tpu.core.windows import WindowPane
    from gelly_streaming_tpu.io import wire
    from gelly_streaming_tpu.library.connected_components import ConnectedComponents

    rng = np.random.default_rng(31)
    n = 1 << 14
    pane = WindowPane(
        window_id=0,
        max_timestamp=0,
        src=rng.integers(0, 1 << 16, n).astype(np.int32),
        dst=rng.integers(0, 1 << 16, n).astype(np.int32),
        val=None,
        time=None,
    )
    runner = MeshAggregationRunner(ConnectedComponents())
    width = wire.width_for_capacity(1 << 16)
    rows, counts, cap = runner._pack_pane_wire(pane, width)
    single_bytes = wire.wire_nbytes(n, width)
    assert rows.nbytes <= 1.5 * single_bytes
    # and per-shard: each shard receives ~1/S of the single path's bytes
    per_shard = rows.nbytes / runner.num_shards
    assert per_shard <= 1.5 * single_bytes / runner.num_shards
    assert counts.sum() == n


def test_cc_mesh_combine_is_collective_and_matches_generic(monkeypatch):
    """CC/bipartiteness supply a collective cross-shard combine (pmin-round
    fixpoint) replacing the all_gather + S-1 sequential merges (VERDICT r3
    weak #2); its fixed point must equal the generic gather+combine fold."""
    import time

    from gelly_streaming_tpu.library import connected_components as cc_mod

    cfg = StreamConfig(vertex_capacity=1 << 15, batch_size=1 << 17)
    assert ConnectedComponents().mesh_combine_states(cfg, "shards") is not None
    rng = np.random.default_rng(3)
    n = 1 << 17
    src = rng.integers(0, cfg.vertex_capacity, n).astype(np.int32)
    dst = rng.integers(0, cfg.vertex_capacity, n).astype(np.int32)

    def run_pane(runner):
        from gelly_streaming_tpu.core.windows import WindowPane
        from gelly_streaming_tpu.io import wire

        pane = WindowPane(0, 0, src, dst, None, None)
        width = wire.width_for_capacity(cfg.vertex_capacity)
        rows, counts, cap = runner._pack_pane_wire(pane, width)
        step = runner._pane_step_wire(cfg, cap, width)
        out = step(rows, counts)  # compile + warm
        import jax

        jax.block_until_ready(out)
        best = float("inf")
        for _ in range(5):
            t0 = time.perf_counter()
            jax.block_until_ready(step(rows, counts))
            best = min(best, time.perf_counter() - t0)
        return out, best

    collective_state, t_collective = run_pane(
        MeshAggregationRunner(ConnectedComponents())
    )
    monkeypatch.setattr(
        cc_mod._CCMixin, "mesh_combine_states", lambda self, cfg, axis: None
    )
    agg = ConnectedComponents()
    assert agg.mesh_combine_states(cfg, "shards") is None
    generic_state, t_generic = run_pane(MeshAggregationRunner(agg))

    from gelly_streaming_tpu.ops import unionfind as uf
    import jax

    lab_c = np.asarray(jax.jit(uf.compress)(collective_state.parent))
    lab_g = np.asarray(jax.jit(uf.compress)(generic_state.parent))
    assert np.array_equal(lab_c, lab_g)
    assert np.array_equal(
        np.asarray(collective_state.seen), np.asarray(generic_state.seen)
    )
    # the pinned scaling claim: the collective combine must not be slower
    # than gather-and-merge (it is ~1.5-2x faster on the 8-CPU mesh; the
    # generous best-of-5 bound absorbs timer noise on a loaded single-core
    # host while still catching an order-of-magnitude regression)
    assert t_collective < t_generic * 1.5, (t_collective, t_generic)


def test_streaming_fold_scaling_shape_fixed_per_shard_volume():
    """Pinned scaling-shape bound for the sharded streaming wire fold
    (VERDICT r4 items 3+9): hold per-shard edge volume FIXED, sweep S, and
    assert the TOTAL rate does not COLLAPSE as S grows.

    On the shared-core virtual mesh every shard timeshares one physical
    core: per-edge compute serializes (S-invariant total rate) and the
    per-collect fixed term (end-of-stream combine + dispatch chain)
    amortizes over S x more edges, so the measured total rate HOLDS OR
    RISES with S (idle-host shape: ~37-42M at S=2 up to ~68-106M at S=8).
    A communication term growing with S — the pathology this pin exists to
    catch — would drop the total rate instead.  One-sided 2.0x tolerance
    absorbs CI load noise; the dryrun (stage D) runs the same sweep at
    larger volume with a 1.5x bound on an otherwise-idle host."""
    import time

    from gelly_streaming_tpu.io import wire

    capacity = 1 << 14
    per_shard = 1 << 16
    batch = 1 << 14
    rng = np.random.default_rng(7)
    rates = {}
    for S in (2, 4, 8):
        n = S * per_shard
        src = rng.integers(0, capacity, n).astype(np.int32)
        dst = rng.integers(0, capacity, n).astype(np.int32)
        width = wire.replay_width(capacity, batch)
        bufs, tail = wire.pack_stream(src, dst, batch, width)
        assert tail is None
        cfg = StreamConfig(
            vertex_capacity=capacity, batch_size=batch, num_shards=S
        )
        out = EdgeStream.from_wire(bufs, batch, width, cfg).aggregate(
            ConnectedComponents()
        )
        out.collect()  # compile pass
        best = float("inf")
        for _ in range(3):
            t0 = time.perf_counter()
            out.collect()
            best = min(best, time.perf_counter() - t0)
        rates[S] = n / best
    assert rates[8] > rates[2] / 2.0, (
        f"sharded streaming fold total rate collapsed with S: "
        f"{ {S: round(r / 1e6, 1) for S, r in rates.items()} }"
    )

"""Seeded configuration fuzz of the packed-wire fast path.

One test sweeps (n_edges, capacity, batch_size, encoding, crash point)
combinations, asserting CC labels against a host union-find every time —
the cheap, wide regression net over the ingest plane's many code paths
(pair40/width-2/EF40 encodings, tail batches, checkpoint resume)."""

import numpy as np
import pytest

import jax

import gelly_streaming_tpu.utils.checkpoint as ckpt
from gelly_streaming_tpu.core.config import StreamConfig
from gelly_streaming_tpu.core.stream import EdgeStream
from gelly_streaming_tpu.library.connected_components import ConnectedComponents
from gelly_streaming_tpu.ops import unionfind as uf


from fixtures import host_min_labels as _host_min_labels


CASES = [
    # (n_edges, capacity, batch, encoding)
    (257, 1 << 6, 64, "plain"),     # tail batch, width-2 wire
    (512, 1 << 6, 64, "ef40"),      # EF40, exact batches
    (999, 1 << 10, 128, "ef40"),    # EF40 with tail
    (300, (1 << 16) + 8, 64, "plain"),  # width-3 wire (no EF40 legal)
    (64, 1 << 18, 64, "plain"),     # pair40 wire, single batch
    (1, 1 << 6, 64, "plain"),       # single edge
]


@pytest.mark.parametrize("n,cap,batch,enc", CASES)
def test_wire_cc_matches_host_union_find(n, cap, batch, enc):
    rng = np.random.default_rng(n * 31 + cap)
    src = rng.integers(0, cap, n).astype(np.int32)
    dst = rng.integers(0, cap, n).astype(np.int32)
    cfg = StreamConfig(vertex_capacity=cap, batch_size=batch, wire_encoding=enc)
    out = EdgeStream.from_arrays(src, dst, cfg).aggregate(ConnectedComponents())
    labels = np.asarray(jax.jit(uf.compress)(out.collect()[-1][0].parent))
    np.testing.assert_array_equal(labels, _host_min_labels(cap, src, dst))


class _Crash(RuntimeError):
    pass


@pytest.mark.parametrize("crash_after,enc", [(1, "plain"), (3, "ef40"), (2, "plain")])
def test_wire_cc_crash_resume_fuzz(tmp_path, monkeypatch, crash_after, enc):
    rng = np.random.default_rng(crash_after * 7)
    n, cap = 800, 128
    src = rng.integers(0, cap, n).astype(np.int32)
    dst = rng.integers(0, cap, n).astype(np.int32)
    cfg = StreamConfig(
        vertex_capacity=cap, batch_size=64, wire_checkpoint_batches=2,
        wire_encoding=enc,
    )
    path = str(tmp_path / f"fz{crash_after}")
    real = ckpt.save_state
    count = {"n": 0}

    def crashing(p, state):
        real(p, state)
        count["n"] += 1
        if count["n"] == crash_after:
            raise _Crash()

    monkeypatch.setattr(ckpt, "save_state", crashing)
    with pytest.raises(_Crash):
        EdgeStream.from_arrays(src, dst, cfg).aggregate(
            ConnectedComponents(), checkpoint_path=path
        ).collect()
    monkeypatch.setattr(ckpt, "save_state", real)
    out = (
        EdgeStream.from_arrays(src, dst, cfg)
        .aggregate(ConnectedComponents(), checkpoint_path=path)
        .collect()
    )
    labels = np.asarray(jax.jit(uf.compress)(out[-1][0].parent))
    np.testing.assert_array_equal(labels, _host_min_labels(cap, src, dst))


REPLAY_CASES = [
    # (n_edges, capacity, batch, width_kind)
    (257, 1 << 6, 64, "bytes"),    # width-2, tail
    (512, 1 << 6, 64, "ef40"),     # EF40, exact batches
    (999, 1 << 10, 128, "ef40"),   # EF40 with tail
    (300, (1 << 20) + 8, 64, "bytes"),  # width-3 (capacity > 2^20)
    (64, 1 << 18, 64, "pair40"),   # pair40, single batch
]


@pytest.mark.parametrize("n,cap,batch,kind", REPLAY_CASES)
def test_replay_cc_matches_host_union_find(n, cap, batch, kind):
    """The replay source under the same configuration sweep as from_arrays."""
    from gelly_streaming_tpu.io import wire as wire_mod

    rng = np.random.default_rng(n * 13 + cap)
    src = rng.integers(0, cap, n).astype(np.int32)
    dst = rng.integers(0, cap, n).astype(np.int32)
    width = {
        "bytes": wire_mod.width_for_capacity(cap),
        "pair40": wire_mod.PAIR40,
        "ef40": (wire_mod.EF40, cap),
    }[kind]
    bufs, tail = wire_mod.pack_stream(src, dst, batch, width)
    cfg = StreamConfig(vertex_capacity=cap, batch_size=batch)
    out = EdgeStream.from_wire(bufs, batch, width, cfg, tail=tail).aggregate(
        ConnectedComponents()
    )
    labels = np.asarray(jax.jit(uf.compress)(out.collect()[-1][0].parent))
    np.testing.assert_array_equal(labels, _host_min_labels(cap, src, dst))

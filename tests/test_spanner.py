"""k-Spanner aggregation tests (library/Spanner.java admission semantics)."""

import pytest

from gelly_streaming_tpu.core.config import StreamConfig
from gelly_streaming_tpu.core.stream import EdgeStream
from gelly_streaming_tpu.library.spanner import Spanner

CFG = StreamConfig(vertex_capacity=32, max_degree=8, num_shards=1)


def test_spanner_admission_sequence():
    # The AdjacencyListGraphTest.testBoundedBFS sequence (:58-85) as a stream:
    # with k=3, edges (3,6) and (5,9) must be dropped, the rest admitted.
    edges = [
        (1, 4), (4, 5), (5, 6), (4, 7), (7, 8),
        (2, 3), (3, 4), (3, 6), (8, 9), (8, 6), (5, 9),
    ]
    stream = EdgeStream.from_collection(edges, CFG)
    results = stream.aggregate(Spanner(window_ms=1000, k=3)).collect()
    g = results[-1][0]
    expected = {
        (1, 4), (4, 5), (5, 6), (4, 7), (7, 8),
        (2, 3), (3, 4), (8, 9), (6, 8),
    }
    assert g.edges() == expected


def test_spanner_k1_keeps_all_non_duplicate_edges():
    # k=1: an edge is dropped only if endpoints are already adjacent.
    edges = [(1, 2), (2, 3), (1, 2), (1, 3)]
    stream = EdgeStream.from_collection(edges, CFG)
    results = stream.aggregate(Spanner(window_ms=1000, k=1)).collect()
    assert results[-1][0].edges() == {(1, 2), (2, 3), (1, 3)}


def test_within_two_matches_bounded_bfs_k2():
    """The O(D^2) k=2 fast path must agree with the dense BFS on random
    tables (review finding: the reference configuration k=2 dispatches to
    within_two, which had no coverage)."""
    import jax
    import numpy as np

    from gelly_streaming_tpu.summaries import adjacency

    rng = np.random.default_rng(4)
    nbrs, deg = adjacency.init_table(64, 8)
    for _ in range(60):
        u, v = rng.integers(0, 64, 2)
        nbrs, deg = adjacency.add_undirected_edge(
            nbrs, deg, jax.numpy.int32(u), jax.numpy.int32(v)
        )
    w2 = jax.jit(adjacency.within_two)
    bfs = jax.jit(adjacency.bounded_bfs, static_argnames="k")
    for _ in range(200):
        a, b = (int(x) for x in rng.integers(0, 64, 2))
        got = bool(w2(nbrs, jax.numpy.int32(a), jax.numpy.int32(b)))
        want = bool(bfs(nbrs, jax.numpy.int32(a), jax.numpy.int32(b), k=2))
        assert got == want, (a, b, got, want)


def test_spanner_k2_matches_sequential_reference():
    """k=2 end-to-end through aggregate(): the admitted spanner equals the
    sequential reference fold (AdjacencyListGraph-based) edge for edge."""
    import numpy as np

    from gelly_streaming_tpu.core.config import StreamConfig
    from gelly_streaming_tpu.core.stream import EdgeStream
    from gelly_streaming_tpu.library.spanner import Spanner
    from gelly_streaming_tpu.summaries.adjacency import AdjacencyListGraph

    rng = np.random.default_rng(9)
    n, c = 600, 48
    src = rng.integers(0, c, n).astype(np.int32)
    dst = rng.integers(0, c, n).astype(np.int32)
    cfg = StreamConfig(vertex_capacity=64, batch_size=64, max_degree=48)
    out = (
        EdgeStream.from_arrays(src, dst, cfg)
        .aggregate(Spanner(1000, k=2))
        .collect()
    )
    got = out[-1][0].edges()

    ref = AdjacencyListGraph(64, 48)
    for u, v in zip(src, dst):
        u, v = int(u), int(v)
        if u == v:
            continue
        if not ref.bounded_bfs(u, v, 2):
            ref.add_edge(u, v)
    assert got == ref.edges()


def test_within_k_balls_matches_bounded_bfs():
    """Exact meet-in-the-middle balls == dense BFS for k=1..4 on random
    tables (the general-k capacity-independent admission body)."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from gelly_streaming_tpu.summaries import adjacency

    rng = np.random.default_rng(6)
    nbrs, deg = adjacency.init_table(48, 6)
    for _ in range(40):
        u, v = rng.integers(0, 48, 2)
        nbrs, deg = adjacency.add_undirected_edge(
            nbrs, deg, jnp.int32(u), jnp.int32(v)
        )
    balls = jax.jit(adjacency.within_k_balls, static_argnames="k")
    bfs = jax.jit(adjacency.bounded_bfs, static_argnames="k")
    # k=5,6 exercise the deep-ball bodies (radius-3 expansions) the
    # crossover usually defers to BFS for — exactness must hold regardless
    # of which body auto picks
    for k in (1, 2, 3, 4, 5, 6):
        for _ in range(80 if k <= 4 else 30):
            a, b = (int(x) for x in rng.integers(0, 48, 2))
            got = bool(balls(nbrs, jnp.int32(a), jnp.int32(b), k=k))
            want = bool(bfs(nbrs, jnp.int32(a), jnp.int32(b), k=k))
            assert got == want, (k, a, b, got, want)


def test_spanner_k3_ball_body_matches_bfs_body(monkeypatch):
    """Force the ball body on a k=3 spanner and compare the admitted edge
    set against the BFS body on the same stream."""
    import numpy as np

    import gelly_streaming_tpu.library.spanner as spanner_mod
    from gelly_streaming_tpu.core.config import StreamConfig
    from gelly_streaming_tpu.core.stream import EdgeStream
    from gelly_streaming_tpu.library.spanner import Spanner

    rng = np.random.default_rng(12)
    src = rng.integers(0, 40, 300).astype(np.int32)
    dst = rng.integers(0, 40, 300).astype(np.int32)
    cfg = StreamConfig(vertex_capacity=64, batch_size=64, max_degree=32)

    def run(force_balls):
        if force_balls:
            monkeypatch.setattr(
                spanner_mod.adjacency, "ball_cost", lambda d, k: 0
            )
        else:
            monkeypatch.setattr(
                spanner_mod.adjacency,
                "ball_cost",
                lambda d, k: 1 << 60,
            )
        agg = Spanner(1000, k=3)
        out = (
            EdgeStream.from_arrays(src, dst, cfg).aggregate(agg).collect()
        )
        return out[-1][0].edges()

    assert run(True) == run(False)


@pytest.mark.parametrize("k", [2, 3])
def test_spanner_on_mesh_is_valid_k_spanner(k):
    """Spanner through the 8-shard mesh runner (per-shard admission +
    CombineSpanners re-insertion, Spanner.java:92-116).  A parallel spanner
    legitimately differs edge-for-edge from the sequential fold, and the
    re-insertion merge guarantees stretch <= k only PER MERGE LEVEL (a
    rejected edge's witness path on the smaller side can itself be rejected
    during the merge, stretching each hop to <= k) — a property inherited
    from the reference's CombineSpanners, not introduced here.  The pin is
    therefore: every admitted edge came from the stream, connectivity of
    every streamed edge is preserved, and stretch stays within k*k (the
    one-merge-level bound; measured max at k=2 on this fixed seed is k+1
    with only 2 of 329 stream edges past k).  k=3 runs the general-k balls
    admission body through the same mesh plane."""
    from collections import deque

    import numpy as np

    from gelly_streaming_tpu.core.config import StreamConfig
    from gelly_streaming_tpu.core.stream import EdgeStream
    from gelly_streaming_tpu.library.spanner import Spanner

    rng = np.random.default_rng(21)
    n, c = 400, 48
    src = rng.integers(0, c, n).astype(np.int32)
    dst = rng.integers(0, c, n).astype(np.int32)
    cfg = StreamConfig(
        vertex_capacity=64, batch_size=64, max_degree=48, num_shards=8
    )
    out = (
        EdgeStream.from_arrays(src, dst, cfg)
        .aggregate(Spanner(1000, k=k))
        .collect()
    )
    spanner_edges = out[-1][0].edges()

    streamed = {
        (min(int(u), int(v)), max(int(u), int(v)))
        for u, v in zip(src, dst)
        if u != v
    }
    assert spanner_edges, "mesh spanner admitted nothing"
    assert set(spanner_edges) <= streamed, "spanner invented an edge"

    adj = {}
    for u, v in spanner_edges:
        adj.setdefault(u, set()).add(v)
        adj.setdefault(v, set()).add(u)

    def dist_within(a, b, bound):
        if a == b:
            return True
        seen = {a}
        frontier = deque([(a, 0)])
        while frontier:
            node, d = frontier.popleft()
            if d == bound:
                continue
            for nxt in adj.get(node, ()):
                if nxt == b:
                    return True
                if nxt not in seen:
                    seen.add(nxt)
                    frontier.append((nxt, d + 1))
        return False

    past_k = 0
    for u, v in streamed:
        if not dist_within(u, v, k):
            past_k += 1
            assert dist_within(u, v, k * k), (
                f"stream edge ({u},{v}) stretched past the merge bound k^2"
            )
    # the overwhelming majority must satisfy the plain k bound (the merge
    # only stretches witnesses broken during re-insertion; fixed seed)
    assert past_k <= max(2, len(streamed) // 50), past_k

"""k-Spanner aggregation tests (library/Spanner.java admission semantics)."""

from gelly_streaming_tpu.core.config import StreamConfig
from gelly_streaming_tpu.core.stream import EdgeStream
from gelly_streaming_tpu.library.spanner import Spanner

CFG = StreamConfig(vertex_capacity=32, max_degree=8, num_shards=1)


def test_spanner_admission_sequence():
    # The AdjacencyListGraphTest.testBoundedBFS sequence (:58-85) as a stream:
    # with k=3, edges (3,6) and (5,9) must be dropped, the rest admitted.
    edges = [
        (1, 4), (4, 5), (5, 6), (4, 7), (7, 8),
        (2, 3), (3, 4), (3, 6), (8, 9), (8, 6), (5, 9),
    ]
    stream = EdgeStream.from_collection(edges, CFG)
    results = stream.aggregate(Spanner(window_ms=1000, k=3)).collect()
    g = results[-1][0]
    expected = {
        (1, 4), (4, 5), (5, 6), (4, 7), (7, 8),
        (2, 3), (3, 4), (8, 9), (6, 8),
    }
    assert g.edges() == expected


def test_spanner_k1_keeps_all_non_duplicate_edges():
    # k=1: an edge is dropped only if endpoints are already adjacent.
    edges = [(1, 2), (2, 3), (1, 2), (1, 3)]
    stream = EdgeStream.from_collection(edges, CFG)
    results = stream.aggregate(Spanner(window_ms=1000, k=1)).collect()
    assert results[-1][0].edges() == {(1, 2), (2, 3), (1, 3)}

// Sanitizer/fuzz harness for the native decode plane (ISSUE 15).
//
// Compiled by tests/test_native_sanitizers.py with
//   g++ -O1 -g -std=c++17 -pthread -fsanitize=address,undefined
//       -fno-sanitize-recover=all
// so every heap overrun, use-after-free, signed overflow, or misaligned
// access in the canonical source aborts the process instead of silently
// corrupting a decode.  Three subcommands:
//
//   selfcheck             deterministic round-trip/invariant checks of the
//                         packers, sorter, BDV encoder, wire decoder, and
//                         the GLY1 prefix probe (the native build gate's
//                         checks, replayed under instrumentation)
//   fuzz <seed> <iters>   structure-aware fuzzing: valid fixed/PAIR40/BDV
//                         buffers and GLY1 prefixes built from a seeded
//                         xorshift PRNG, then mutated (byte flips, size
//                         lies, truncations) and fed to the decode plane.
//                         Buffers are heap-allocated at EXACTLY the size
//                         the decoder is told, so any read past nbytes is
//                         an ASan abort, not luck.
//   replay <file>...      byte-for-byte replay of persisted regression
//                         inputs (tests/fuzz_corpus/*.bin, GFZ1 format —
//                         see tests/fuzz_corpus/README.md)
//
// Exit 0 means no sanitizer report and no invariant violation.  The
// harness never asserts WHICH verdict a mutated buffer gets (that parity
// is the tier-1 numpy-oracle replay's job) — only that the decoder
// refuses or accepts without touching memory it does not own.

#include "../gelly_streaming_tpu/native_src/edge_parser.cpp"

#include <cinttypes>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <vector>
#include <algorithm>

namespace {

uint64_t g_rng_state = 0x9E3779B97F4A7C15ull;

uint64_t rng() {
  uint64_t x = g_rng_state;
  x ^= x << 13;
  x ^= x >> 7;
  x ^= x << 17;
  g_rng_state = x;
  return x;
}

uint32_t rng_below(uint32_t bound) {
  return bound ? (uint32_t)(rng() % bound) : 0;
}

[[noreturn]] void die(const char* what) {
  fprintf(stderr, "harness invariant violated: %s\n", what);
  exit(1);
}

void check(bool ok, const char* what) {
  if (!ok) die(what);
}

// Exact-size heap copy: the decoder is told `nbytes`, and that is the
// allocation's true extent — ASan turns any overrun into an abort.
struct ExactBuf {
  uint8_t* p;
  int64_t n;
  explicit ExactBuf(int64_t nbytes) : n(nbytes) {
    p = static_cast<uint8_t*>(malloc(nbytes > 0 ? (size_t)nbytes : 1));
    if (!p) die("harness oom");
  }
  ~ExactBuf() { free(p); }
  ExactBuf(const ExactBuf&) = delete;
  ExactBuf& operator=(const ExactBuf&) = delete;
};

int64_t bdv_worst_case(int64_t n) { return (2 * n + 3) / 4 + 8 * n; }

// Build one valid wire buffer for `code` over ids < capacity; returns the
// byte size and fills src/dst with the encoded edges.
int64_t build_valid(int code, int64_t n, int32_t capacity,
                    std::vector<int32_t>& src, std::vector<int32_t>& dst,
                    std::vector<uint8_t>& out) {
  src.resize((size_t)n);
  dst.resize((size_t)n);
  for (int64_t i = 0; i < n; ++i) {
    src[(size_t)i] = (int32_t)rng_below((uint32_t)capacity);
    dst[(size_t)i] = (int32_t)rng_below((uint32_t)capacity);
  }
  if (code >= 2 && code <= 4) {
    out.resize((size_t)(2 * n * code));
    int64_t wrote = pack_edges(src.data(), dst.data(), n, code, out.data());
    check(wrote == (int64_t)out.size(), "pack_edges size");
    return wrote;
  }
  if (code == 5) {
    out.resize((size_t)(5 * n));
    int64_t wrote = pack_edges40(src.data(), dst.data(), n, out.data());
    check(wrote == (int64_t)out.size(), "pack_edges40 size");
    return wrote;
  }
  // BDV: encode a (dst, src)-sorted copy; buffer sized at the worst case
  std::vector<int32_t> ss((size_t)n), dd((size_t)n);
  if (n > 0) {
    check(sort_edges_dst_src(src.data(), dst.data(), n, capacity, ss.data(),
                             dd.data()) == n,
          "sorter refused valid input");
  }
  src = ss;
  dst = dd;
  out.resize((size_t)bdv_worst_case(n) + 1);
  int64_t wrote = encode_edges_bdv(src.data(), dst.data(), n, out.data(),
                                   (int64_t)out.size());
  check(wrote >= 0, "encoder refused sorted input");
  out.resize((size_t)wrote);
  return wrote;
}

// Decode with exact-extent buffers and exact-size outputs; verdicts are
// sanity-bounded, accepted ids must be in range.
void run_decode(const uint8_t* bytes, int64_t nbytes, int64_t n, int code,
                int32_t capacity, int32_t sort) {
  if (n < 0 || n > (int64_t)1 << 22) return;
  ExactBuf buf(nbytes);
  if (nbytes > 0) memcpy(buf.p, bytes, (size_t)nbytes);
  std::vector<int32_t> os((size_t)n), od((size_t)n);
  int64_t rc = decode_wire_into(buf.p, nbytes, n, code, capacity, sort,
                                os.data(), od.data());
  check(rc == n || (rc >= -4 && rc < 0), "decode verdict out of taxonomy");
  if (rc == n) {
    for (int64_t i = 0; i < n; ++i) {
      check((uint32_t)os[(size_t)i] < (uint32_t)capacity &&
                (uint32_t)od[(size_t)i] < (uint32_t)capacity,
            "accepted id out of range");
    }
  }
}

// ---------------------------------------------------------------------------

int selfcheck() {
  // GLY1 probe taxonomy over its whole refusal surface
  {
    uint8_t p[12] = {'G', 'L', 'Y', '1', 0, 0, 0, 7, 0, 0, 0, 9};
    int64_t h = -1, pl = -1;
    check(gly1_probe_prefix(p, 1 << 16, 1 << 26, &h, &pl) == 0, "probe ok");
    check(h == 7 && pl == 9, "probe lengths");
    p[0] = 'X';
    check(gly1_probe_prefix(p, 1 << 16, 1 << 26, &h, &pl) == -1, "probe magic");
    p[0] = 'G';
    check(gly1_probe_prefix(p, 6, 1 << 26, &h, &pl) == -2, "probe header cap");
    check(gly1_probe_prefix(p, 1 << 16, 8, &h, &pl) == -3, "probe payload cap");
  }
  // every push encoding round-trips through the decoder, including the
  // decode+bin fused pass and the n == 0 edge
  const int codes[] = {2, 3, 4, 5, 6};
  const int32_t caps[] = {1 << 14, 1 << 20, 1 << 20, 1 << 20, 1 << 12};
  for (int k = 0; k < 5; ++k) {
    int code = codes[k];
    int32_t cap = caps[k];
    for (int64_t n : {(int64_t)0, (int64_t)1, (int64_t)513}) {
      std::vector<int32_t> src, dst;
      std::vector<uint8_t> wire;
      int64_t nbytes = build_valid(code, n, cap, src, dst, wire);
      ExactBuf buf(nbytes);
      if (nbytes > 0) memcpy(buf.p, wire.data(), (size_t)nbytes);
      std::vector<int32_t> os((size_t)n), od((size_t)n);
      int64_t rc =
          decode_wire_into(buf.p, nbytes, n, code, cap, 0, os.data(), od.data());
      check(rc == n, "valid buffer refused");
      for (int64_t i = 0; i < n; ++i) {
        check(os[(size_t)i] == src[(size_t)i] && od[(size_t)i] == dst[(size_t)i],
              "decode drifted from encode");
      }
      // fused decode+bin equals decode-then-sort
      std::vector<int32_t> bs((size_t)n), bd((size_t)n);
      rc = decode_wire_into(buf.p, nbytes, n, code, cap, 1, bs.data(), bd.data());
      check(rc == n, "fused binning refused valid buffer");
      std::vector<int32_t> es((size_t)n), ed((size_t)n);
      if (n > 0) {
        check(sort_edges_dst_src(src.data(), dst.data(), n, cap, es.data(),
                                 ed.data()) == n,
              "sorter refused");
      }
      for (int64_t i = 0; i < n; ++i) {
        check(bs[(size_t)i] == es[(size_t)i] && bd[(size_t)i] == ed[(size_t)i],
              "fused binning drifted from two-pass");
      }
    }
  }
  // sorter: output is (dst, src)-nondecreasing and the same multiset
  {
    int64_t n = 4096;
    int32_t cap = 1 << 20;
    std::vector<int32_t> s((size_t)n), d((size_t)n), os((size_t)n), od((size_t)n);
    for (int64_t i = 0; i < n; ++i) {
      s[(size_t)i] = (int32_t)rng_below((uint32_t)cap);
      d[(size_t)i] = (int32_t)rng_below((uint32_t)cap);
    }
    check(sort_edges_dst_src(s.data(), d.data(), n, cap, os.data(), od.data()) ==
              n,
          "sorter refused valid");
    std::vector<uint64_t> want((size_t)n), got((size_t)n);
    for (int64_t i = 0; i < n; ++i) {
      want[(size_t)i] = ((uint64_t)(uint32_t)d[(size_t)i] << 32) |
                        (uint32_t)s[(size_t)i];
      got[(size_t)i] = ((uint64_t)(uint32_t)od[(size_t)i] << 32) |
                       (uint32_t)os[(size_t)i];
    }
    for (int64_t i = 1; i < n; ++i) {
      check(got[(size_t)i - 1] <= got[(size_t)i], "sorter order");
    }
    std::sort(want.begin(), want.end());
    std::vector<uint64_t> got_sorted = got;
    std::sort(got_sorted.begin(), got_sorted.end());
    check(want == got_sorted, "sorter multiset");
    // out-of-range ids refuse instead of scribbling count tables
    s[0] = cap;
    check(sort_edges_dst_src(s.data(), d.data(), n, cap, os.data(), od.data()) ==
              -1,
          "sorter accepted out-of-range id");
  }
  // EF40 pack stays inside its declared out_cap; the short-buffer refusal
  // happens before any write
  {
    int64_t n = 1021;
    int32_t cap = 1 << 16;
    std::vector<int32_t> s((size_t)n), d((size_t)n);
    for (int64_t i = 0; i < n; ++i) {
      s[(size_t)i] = (int32_t)rng_below((uint32_t)cap);
      d[(size_t)i] = (int32_t)rng_below((uint32_t)cap);
    }
    int64_t out_cap = (n + cap + 7) / 8 + ((n + 1) / 2) * 5;
    ExactBuf out(out_cap);
    int64_t wrote =
        pack_edges_ef40(s.data(), d.data(), n, cap, out.p, out_cap);
    check(wrote == out_cap, "ef40 size");
    check(pack_edges_ef40(s.data(), d.data(), n, cap, out.p, out_cap - 1) == -1,
          "ef40 accepted short buffer");
  }
  // route_edges conserves edges and respects the floored modulo
  {
    int64_t n = 777;
    int32_t shards = 5;
    int64_t cap = n;
    std::vector<int32_t> s((size_t)n), d((size_t)n);
    std::vector<int32_t> os((size_t)(shards * cap)), od((size_t)(shards * cap));
    std::vector<int64_t> counts((size_t)shards);
    for (int64_t i = 0; i < n; ++i) {
      s[(size_t)i] = (int32_t)rng_below(1 << 20);
      d[(size_t)i] = (int32_t)rng_below(1 << 20);
    }
    check(route_edges(s.data(), d.data(), n, shards, 1, cap, os.data(),
                      od.data(), counts.data()) == n,
          "router lost edges");
  }
  // cc_baseline labels are a fixpoint (every label points at itself)
  {
    int32_t cap = 512;
    int64_t n = 2048;
    std::vector<int32_t> s((size_t)n), d((size_t)n), parent((size_t)cap);
    for (int64_t i = 0; i < n; ++i) {
      s[(size_t)i] = (int32_t)rng_below((uint32_t)cap);
      d[(size_t)i] = (int32_t)rng_below((uint32_t)cap);
    }
    check(cc_baseline(s.data(), d.data(), n, parent.data(), cap) >= 0,
          "cc_baseline failed");
    for (int32_t v = 0; v < cap; ++v) {
      check(parent[(size_t)parent[(size_t)v]] == parent[(size_t)v],
            "cc labels not flattened");
    }
  }
  printf("selfcheck ok\n");
  return 0;
}

int fuzz(uint64_t seed, int64_t iters) {
  g_rng_state = seed ? seed : 1;
  for (int64_t it = 0; it < iters; ++it) {
    uint32_t pick = rng_below(100);
    if (pick < 70) {
      // mutated wire buffer through the full decode plane
      const int codes[] = {2, 3, 4, 5, 6};
      int code = codes[rng_below(5)];
      int64_t n = rng_below(1024);
      int32_t cap = 1 + (int32_t)rng_below(code == 6 ? (1u << 20) : (1u << 16));
      std::vector<int32_t> src, dst;
      std::vector<uint8_t> wire;
      int64_t nbytes = build_valid(code, n, cap, src, dst, wire);
      // mutate: byte flips, then maybe lie about the size / batch / cap
      uint32_t flips = rng_below(8);
      for (uint32_t f = 0; f < flips && nbytes > 0; ++f) {
        wire[(size_t)rng_below((uint32_t)nbytes)] ^= (uint8_t)(1 + rng_below(255));
      }
      int64_t claim_bytes = nbytes;
      int64_t claim_n = n;
      int32_t claim_cap = cap;
      switch (rng_below(6)) {
        case 0:
          claim_bytes = (int64_t)rng_below((uint32_t)nbytes + 16);
          break;
        case 1:
          claim_n = (int64_t)rng_below((uint32_t)n + 8);
          break;
        case 2:
          claim_cap = 1 + (int32_t)rng_below(1 << 10);
          break;
        default:
          break;
      }
      if (claim_bytes > (int64_t)wire.size()) {
        wire.resize((size_t)claim_bytes);  // extension bytes are PRNG junk
        for (int64_t k = nbytes; k < claim_bytes; ++k) {
          wire[(size_t)k] = (uint8_t)rng();
        }
      }
      run_decode(wire.data(), claim_bytes, claim_n, code, claim_cap,
                 (int32_t)rng_below(2));
    } else if (pick < 85) {
      // GLY1 prefixes: valid magic half the time, junk otherwise
      ExactBuf p(12);
      for (int k = 0; k < 12; ++k) p.p[k] = (uint8_t)rng();
      if (rng_below(2)) memcpy(p.p, "GLY1", 4);
      int64_t h = 0, pl = 0;
      int32_t rc = gly1_probe_prefix(p.p, 1 << 16, 1 << 26, &h, &pl);
      check(rc == 0 || (rc >= -3 && rc < 0), "probe verdict out of taxonomy");
    } else if (pick < 95) {
      // encoder: arbitrary (not necessarily sorted) input must refuse or
      // stay inside the worst-case buffer
      int64_t n = rng_below(512);
      std::vector<int32_t> s((size_t)n), d((size_t)n);
      for (int64_t i = 0; i < n; ++i) {
        s[(size_t)i] = (int32_t)rng_below(1 << 20);
        d[(size_t)i] = (int32_t)rng_below(1 << 20);
      }
      if (rng_below(2) && n > 1) std::sort(d.begin(), d.end());
      int64_t cap_bytes = bdv_worst_case(n);
      ExactBuf out(cap_bytes);
      int64_t wrote =
          encode_edges_bdv(s.data(), d.data(), n, out.p, cap_bytes);
      check(wrote <= cap_bytes, "encoder overran its declared worst case");
    } else {
      // sorter with hostile ids: must refuse, never index the tables
      int64_t n = 1 + rng_below(512);
      int32_t cap = 1 + (int32_t)rng_below(1 << 16);
      std::vector<int32_t> s((size_t)n), d((size_t)n), os((size_t)n),
          od((size_t)n);
      for (int64_t i = 0; i < n; ++i) {
        s[(size_t)i] = (int32_t)(rng() & 0x7FFFFFFF) - (int32_t)rng_below(4);
        d[(size_t)i] = (int32_t)rng_below((uint32_t)cap);
      }
      int64_t rc =
          sort_edges_dst_src(s.data(), d.data(), n, cap, os.data(), od.data());
      check(rc == n || rc == -1, "sorter verdict out of taxonomy");
    }
  }
  printf("fuzz ok (%" PRId64 " iters)\n", iters);
  return 0;
}

uint32_t rd_u32le(const uint8_t* p) {
  return (uint32_t)p[0] | ((uint32_t)p[1] << 8) | ((uint32_t)p[2] << 16) |
         ((uint32_t)p[3] << 24);
}

int replay(const char* path) {
  FILE* f = fopen(path, "rb");
  if (!f) {
    fprintf(stderr, "replay: cannot open %s\n", path);
    return 1;
  }
  std::vector<uint8_t> data;
  uint8_t chunk[4096];
  size_t r;
  while ((r = fread(chunk, 1, sizeof(chunk), f)) > 0) {
    data.insert(data.end(), chunk, chunk + r);
  }
  fclose(f);
  if (data.size() < 16 || memcmp(data.data(), "GFZ1", 4) != 0) {
    fprintf(stderr, "replay: %s is not a GFZ1 corpus file\n", path);
    return 1;
  }
  uint8_t mode = data[4];
  uint8_t code = data[5];
  uint8_t sort = data[6];
  uint32_t n = rd_u32le(&data[8]);
  uint32_t cap = rd_u32le(&data[12]);
  const uint8_t* payload = data.data() + 16;
  int64_t payload_len = (int64_t)data.size() - 16;
  if (mode == 1) {
    if (n > (1u << 22)) {
      fprintf(stderr, "replay: %s claims an absurd batch\n", path);
      return 1;
    }
    run_decode(payload, payload_len, (int64_t)n, (int)code, (int32_t)cap,
               (int32_t)sort);
    printf("replay %s: decode done\n", path);
    return 0;
  }
  if (mode == 2) {
    if (payload_len < 12) {
      fprintf(stderr, "replay: %s prefix under 12 bytes\n", path);
      return 1;
    }
    ExactBuf p(12);
    memcpy(p.p, payload, 12);
    int64_t h = 0, pl = 0;
    int32_t rc = gly1_probe_prefix(p.p, (int64_t)n, (int64_t)cap, &h, &pl);
    check(rc == 0 || (rc >= -3 && rc < 0), "probe verdict out of taxonomy");
    printf("replay %s: probe rc=%d\n", path, rc);
    return 0;
  }
  fprintf(stderr, "replay: %s has unknown mode %u\n", path, mode);
  return 1;
}

}  // namespace

int main(int argc, char** argv) {
  if (argc >= 2 && strcmp(argv[1], "selfcheck") == 0) {
    return selfcheck();
  }
  if (argc >= 4 && strcmp(argv[1], "fuzz") == 0) {
    return fuzz(strtoull(argv[2], nullptr, 10), strtoll(argv[3], nullptr, 10));
  }
  if (argc >= 3 && strcmp(argv[1], "replay") == 0) {
    int rc = 0;
    for (int k = 2; k < argc; ++k) rc |= replay(argv[k]);
    return rc;
  }
  fprintf(stderr,
          "usage: %s selfcheck | fuzz <seed> <iters> | replay <file>...\n",
          argv[0]);
  return 2;
}

"""Lost-update repro (tier-1): the pipeline counters and the throughput
meter take concurrent bumps from the pack / transfer / drain threads
without dropping any.

The unguarded ``self.edges += n`` read-modify-write has a preemption window
between the LOAD and the STORE; with the switch interval cranked down the
window is hit reliably, so these tests FAIL (flakily, the nature of the
bug) without the locks and pass deterministically with them — the
lock-discipline analyzer pass (tests/test_analysis.py) pins the guard
statically so the fix cannot quietly regress either way.
"""

import sys
import threading

import pytest

from gelly_streaming_tpu.utils import metrics

THREADS = 8
ITERS = 5000


def _hammer(fn):
    """Run ``fn`` from THREADS threads with an aggressive switch interval."""
    old = sys.getswitchinterval()
    sys.setswitchinterval(1e-5)
    try:
        start = threading.Barrier(THREADS)

        def worker():
            start.wait()
            for _ in range(ITERS):
                fn()

        ts = [threading.Thread(target=worker) for _ in range(THREADS)]
        for t in ts:
            t.start()
        for t in ts:
            t.join()
    finally:
        sys.setswitchinterval(old)


@pytest.mark.timeout_cap(120)
def test_throughput_meter_no_lost_updates():
    meter = metrics.ThroughputMeter()
    _hammer(lambda: meter.record_batch(3))
    assert meter.edges == 3 * THREADS * ITERS
    assert meter.batches == THREADS * ITERS


@pytest.mark.timeout_cap(120)
def test_pipeline_counters_no_lost_updates():
    metrics.reset_pipeline_stats()
    try:
        _hammer(
            lambda: metrics.pipeline_add("pipeline_windows_dispatched", 1)
        )
        stats = metrics.pipeline_stats()
        assert stats["pipeline_windows_dispatched"] == THREADS * ITERS
    finally:
        # process-global counters: leave them zeroed for other tests
        metrics.reset_pipeline_stats()


@pytest.mark.timeout_cap(120)
def test_job_counters_no_lost_updates_no_cross_job_bleed():
    """The job runtime's per-job registries (ISSUE 5): THREADS workers bump
    TWO jobs' counters concurrently — each job's count must be exact (no
    lost updates) and exactly its own (no bleed between job ids), with the
    module totals preserved as the sum."""
    metrics.reset_job_stats()
    try:
        flip = [0]
        flip_lock = threading.Lock()

        def bump():
            with flip_lock:
                flip[0] += 1
                jid = "job-a" if flip[0] % 2 else "job-b"
            metrics.job_add(jid, "job_records", 1)
            metrics.job_add(jid, "job_edges", 3)

        _hammer(bump)
        total = THREADS * ITERS
        a = metrics.job_stats("job-a")
        b = metrics.job_stats("job-b")
        assert a["job_records"] + b["job_records"] == total
        assert a["job_records"] == total // 2 + (total % 2)
        assert b["job_records"] == total // 2
        assert a["job_edges"] == 3 * a["job_records"]
        assert b["job_edges"] == 3 * b["job_records"]
        totals = metrics.job_totals()
        assert totals["job_records"] == total
        assert totals["job_edges"] == 3 * total
    finally:
        metrics.reset_job_stats()


@pytest.mark.timeout_cap(120)
def test_job_high_water_is_per_job_max_under_contention():
    metrics.reset_job_stats()
    try:
        values = list(range(THREADS * ITERS))
        it_lock = threading.Lock()

        def bump():
            with it_lock:
                v = values.pop()
            # odd values to one job, even to the other: each registry must
            # keep ITS OWN max, the module aggregate the global max
            metrics.job_high_water(
                "hwm-odd" if v % 2 else "hwm-even", "job_queue_depth_hwm", v
            )

        _hammer(bump)
        top = THREADS * ITERS - 1
        odd = metrics.job_stats("hwm-odd")["job_queue_depth_hwm"]
        even = metrics.job_stats("hwm-even")["job_queue_depth_hwm"]
        assert {odd, even} == {top, top - 1}
        assert metrics.job_totals()["job_queue_depth_hwm"] == top
    finally:
        metrics.reset_job_stats()


@pytest.mark.timeout_cap(120)
def test_pipeline_high_water_is_max_under_contention():
    metrics.reset_pipeline_stats()
    try:
        values = list(range(THREADS * ITERS))
        it_lock = threading.Lock()

        def bump():
            with it_lock:
                v = values.pop()
            metrics.pipeline_high_water("pipeline_inflight_high_water", v)

        _hammer(bump)
        stats = metrics.pipeline_stats()
        assert stats["pipeline_inflight_high_water"] == THREADS * ITERS - 1
    finally:
        metrics.reset_pipeline_stats()


@pytest.mark.timeout_cap(120)
def test_histogram_registry_no_lost_updates_no_cross_scope_bleed():
    """The bounded latency histograms (ISSUE 9): THREADS workers record
    into TWO job scopes concurrently — every sample lands exactly once in
    its own job's histogram AND the global one (no lost bucket bumps, no
    bleed between scopes)."""
    metrics.reset_histograms()
    try:
        flip = [0]
        flip_lock = threading.Lock()

        def bump():
            with flip_lock:
                flip[0] += 1
                jid = "hist-a" if flip[0] % 2 else "hist-b"
            metrics.hist_record("window_close_to_emission_ms", 1.0, job=jid)

        _hammer(bump)
        total = THREADS * ITERS
        snap = metrics.hist_snapshot()
        a = snap["jobs"]["hist-a"]["window_close_to_emission_ms"]["count"]
        b = snap["jobs"]["hist-b"]["window_close_to_emission_ms"]["count"]
        assert a + b == total
        assert a == total // 2 + (total % 2)
        assert b == total // 2
        assert (
            snap["global"]["window_close_to_emission_ms"]["count"] == total
        )
    finally:
        metrics.reset_histograms()


@pytest.mark.timeout_cap(120)
def test_flight_recorder_ring_no_lost_records():
    """The span ring (ISSUE 9): THREADS drain threads record spans into
    one fixed-capacity ring concurrently — the recorded count is exact
    (no lost slot writes under the '# guarded-by:' lock), the ring holds
    exactly its capacity, and the stage aggregates saw every span."""
    from gelly_streaming_tpu.utils import tracing

    rec = tracing.FlightRecorder(capacity=64)

    def bump():
        span = tracing.WindowSpan(1, "hammer", 0)
        span.mark("dispatch", span.t0)
        rec.record(span)

    _hammer(bump)
    total = THREADS * ITERS
    stats = rec.stats()
    assert stats["recorded"] == total
    assert stats["held"] == 64
    assert len(rec.last(1000)) == 64
    assert stats["stages"]["hammer"]["total"]["count"] == total
    assert stats["stages"]["hammer"]["dispatch"]["count"] == total

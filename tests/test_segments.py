"""Unit tests for the key-grouping primitives (ops/segments.py)."""

import jax.numpy as jnp
import numpy as np

from gelly_streaming_tpu.ops import segments


def _ranks_ref(keys, mask):
    seen = {}
    out = []
    for k, m in zip(keys, mask):
        if not m:
            out.append(0)
            continue
        out.append(seen.get(k, 0))
        seen[k] = seen.get(k, 0) + 1
    return out


def test_occurrence_rank_simple():
    keys = jnp.array([5, 3, 5, 5, 3, 9], jnp.int32)
    ranks = segments.occurrence_rank(keys)
    np.testing.assert_array_equal(np.asarray(ranks), [0, 0, 1, 2, 1, 0])


def test_occurrence_rank_masked():
    keys = jnp.array([5, 5, 5, 5], jnp.int32)
    mask = jnp.array([True, False, True, True])
    ranks = segments.occurrence_rank(keys, mask)
    valid = np.asarray(ranks)[np.asarray(mask)]
    np.testing.assert_array_equal(valid, [0, 1, 2])


def test_occurrence_rank_random_vs_reference():
    rng = np.random.default_rng(0)
    keys = rng.integers(0, 17, size=256).astype(np.int32)
    mask = rng.random(256) < 0.8
    got = np.asarray(segments.occurrence_rank(jnp.asarray(keys), jnp.asarray(mask)))
    want = _ranks_ref(keys, mask)
    np.testing.assert_array_equal(got[mask], np.array(want)[mask])


def test_first_occurrence_mask():
    keys = jnp.array([1, 2, 1, 3, 2, 1], jnp.int32)
    mask = jnp.array([True, True, True, False, True, True])
    first = np.asarray(segments.first_occurrence_mask(keys, mask))
    np.testing.assert_array_equal(first, [True, True, False, False, False, False])


def test_group_counts_and_segment_sum():
    keys = jnp.array([0, 1, 1, 2, 2, 2], jnp.int32)
    mask = jnp.array([True, True, True, True, True, False])
    counts = np.asarray(segments.group_counts(keys, 4, mask))
    np.testing.assert_array_equal(counts, [1, 2, 2, 0])
    vals = jnp.array([10, 1, 2, 3, 4, 100], jnp.int32)
    sums = np.asarray(segments.segment_sum(vals, keys, 4, mask))
    np.testing.assert_array_equal(sums, [10, 3, 7, 0])


def test_sort_by_key_groups_valid_first():
    keys = jnp.array([7, 2, 7, 2], jnp.int32)
    mask = jnp.array([True, True, False, True])
    order, sk = segments.sort_by_key(keys, mask)
    order = np.asarray(order)
    # valid rows first: key-2 rows (1, 3) then key-7 row (0); padding row 2 last
    np.testing.assert_array_equal(order, [1, 3, 0, 2])
    b = np.asarray(segments.segment_boundaries(sk))
    np.testing.assert_array_equal(b, [True, False, True, True])

"""Sampling triangle estimator tests (broadcast + incidence variants)."""

import numpy as np

from gelly_streaming_tpu.core.config import StreamConfig
from gelly_streaming_tpu.core.stream import EdgeStream
from gelly_streaming_tpu.library.sampled_triangles import (
    BroadcastTriangleCount,
    IncidenceSamplingTriangleCount,
)

CFG = StreamConfig(vertex_capacity=16, max_degree=16)


def _complete_graph(n):
    return [(i, j) for i in range(n) for j in range(i + 1, n)]


def test_star_graph_estimates_zero():
    # A star has no triangles: every beta stays 0 -> estimate exactly 0.
    edges = [(0, i) for i in range(1, 10)]
    algo = BroadcastTriangleCount(num_samplers=256)
    estimates = [e[0] for e in algo.run(EdgeStream.from_collection(edges, CFG)).collect()]
    assert estimates[-1] == 0.0


def test_complete_graph_estimate_positive():
    # K8 is triangle-rich; with many samplers some lanes close their wedge.
    algo = BroadcastTriangleCount(num_samplers=1024, seed=7)
    stream = EdgeStream.from_collection(_complete_graph(8), CFG)
    estimates = [e[0] for e in algo.run(stream).collect()]
    assert estimates[-1] > 0.0


def test_incidence_variant_runs():
    algo = IncidenceSamplingTriangleCount(num_samplers=128)
    stream = EdgeStream.from_collection(_complete_graph(6), CFG)
    estimates = algo.run(stream).collect()
    assert len(estimates) == 1 and estimates[0][0] >= 0.0


def test_edge_and_vertex_tracking():
    algo = BroadcastTriangleCount(num_samplers=8)
    stream = EdgeStream.from_collection([(1, 2), (2, 3)], CFG)
    algo.run(stream).collect()
    state = algo.final_state
    assert int(state.edges_seen) == 2
    assert int(np.asarray(state.seen).sum()) == 3

"""Cross-tenant fused dispatch (ISSUE 16): same-shape windows from N jobs
stack into one vmapped mega-fold.

The contract under test: with ``cfg.fused_dispatch`` on, jobs on the plain
windowed plane emit BIT-IDENTICAL record sequences to the solo-dispatch
oracle (``fused_dispatch=0`` — today's path, unchanged); jobs on every
other plane (wire, async, sharded) are untouched by the flag; mixed-shape
cohorts fuse peers and solo loners; a slow sink only skips its own rows;
cancel and pause/resume mid-cohort never drop or duplicate a window; and
tenancy varying 1..16 jobs-per-dispatch causes 0 recompiles once the pow2
row buckets are warm.

Every threaded test carries ``timeout_cap`` (tests/conftest.py): a wedged
scheduler or cohort cycle must FAIL the test, not hang tier-1.
"""

import dataclasses
import threading

import numpy as np
import pytest

import jax.numpy as jnp

from gelly_streaming_tpu.core import compile_cache
from gelly_streaming_tpu.core.aggregation import SummaryBulkAggregation
from gelly_streaming_tpu.core.config import RuntimeConfig, StreamConfig
from gelly_streaming_tpu.core.stream import EdgeStream
from gelly_streaming_tpu.core.windows import FoldRequest
from gelly_streaming_tpu.library.connected_components import (
    ConnectedComponents,
)
from gelly_streaming_tpu.runtime import JobManager, JobState
from gelly_streaming_tpu.utils import metrics

pytestmark = pytest.mark.timeout_cap(300)

CAP = 1 << 12
WIN = 1 << 10
# misaligned batch -> the windowed runtime's ingestion panes (the one plane
# fused dispatch replaces); fused_dispatch pinned explicitly both ways so
# ambient GELLY_FUSED_DISPATCH can never flip the oracle
CFG_SOLO = StreamConfig(
    vertex_capacity=CAP,
    batch_size=(1 << 9) + 96,
    ingest_window_edges=WIN,
    fused_dispatch=0,
)
CFG_FUSED = dataclasses.replace(CFG_SOLO, fused_dispatch=1)


def _graph(seed: int, n: int, cap: int = CAP):
    rng = np.random.default_rng(seed)
    return (
        rng.integers(0, cap, n).astype(np.int32),
        rng.integers(0, cap, n).astype(np.int32),
    )


def _cc_serial(cfg, s, d):
    out = EdgeStream.from_arrays(s, d, cfg).aggregate(ConnectedComponents())
    return [np.asarray(rec[0].parent) for rec in out]


def _materialize_cc(records):
    return [np.asarray(rec[0].parent) for rec in records]


def _assert_windows_equal(want, got, label):
    assert len(want) == len(got), (label, len(want), len(got))
    for w, (a, b) in enumerate(zip(want, got)):
        assert np.array_equal(a, b), f"{label} window {w} diverged"


class EdgeCount(SummaryBulkAggregation):
    """A second descriptor family (distinct cache token): its windows must
    never share a cohort with ConnectedComponents'."""

    order_free = True

    @property
    def cache_token(self):
        return type(self)

    def initial_state(self, cfg):
        return jnp.zeros((), jnp.int32)

    def update(self, state, src, dst, val, mask):
        return state + jnp.sum(mask.astype(jnp.int32))

    def combine(self, a, b):
        return a + b


# ---------------------------------------------------------------------------
# fused vs solo emission parity, per plane
# ---------------------------------------------------------------------------


def _gated_stream(s, d, cfg, release):
    """A windowed-plane stream whose first batch waits for ``release``:
    jobs submitted before the release all reach their first window
    together, so cohort formation is deterministic rather than a race
    against submission latency."""
    from gelly_streaming_tpu.core.types import EdgeBatch

    bs = cfg.batch_size

    def factory():
        release.wait(timeout=60)
        for o in range(0, len(s), bs):
            yield EdgeBatch.from_arrays(s[o : o + bs], d[o : o + bs], pad_to=bs)

    return EdgeStream.from_batches(factory, cfg)


@pytest.mark.parametrize("n_jobs", [2, 4, 16])
def test_fused_matches_solo_windowed_plane(n_jobs):
    windows = 4 if n_jobs == 16 else 8
    datasets = [_graph(seed, windows * WIN) for seed in range(n_jobs)]
    serial = [_cc_serial(CFG_SOLO, s, d) for s, d in datasets]
    metrics.reset_fused_dispatch_stats()
    release = threading.Event()
    with JobManager(RuntimeConfig(max_jobs=n_jobs)) as jm:
        jobs = [
            jm.submit_aggregation(
                _gated_stream(s, d, CFG_FUSED, release),
                ConnectedComponents(),
                name=f"cc-{i}",
            )
            for i, (s, d) in enumerate(datasets)
        ]
        release.set()
        outs = [_materialize_cc(job.results()) for job in jobs]
        states = [job.state for job in jobs]
        status = jm.status()
    assert states == [JobState.DONE] * n_jobs
    for i, (want, got) in enumerate(zip(serial, outs)):
        _assert_windows_equal(want, got, f"job {i}")
    stats = metrics.fused_dispatch_stats()
    assert stats["fused_dispatches"] >= 1, stats
    assert stats["fused_jobs_per_dispatch_hwm"] <= n_jobs, stats
    # the per-job attribution satellite: every fused window is credited to
    # its own job's status row
    total_fused = sum(
        row["fused_windows"] for row in status["jobs"].values()
    )
    assert total_fused == stats["fused_jobs_total"], (status, stats)


@pytest.mark.parametrize(
    "plane,cfg",
    [
        (
            "wire",  # aligned batch -> packed-wire fast path
            StreamConfig(
                vertex_capacity=CAP,
                batch_size=1 << 9,
                ingest_window_edges=WIN,
                fused_dispatch=1,
            ),
        ),
        (
            "async",  # async window pipeline keeps its own plane
            dataclasses.replace(CFG_FUSED, async_windows=2),
        ),
        (
            "sharded",  # owner-sharded mesh plane keeps its own plane
            StreamConfig(
                vertex_capacity=CAP,
                batch_size=1 << 9,
                num_shards=2,
                fused_dispatch=1,
            ),
        ),
    ],
)
def test_fused_flag_leaves_other_planes_bit_identical(plane, cfg):
    """``fused_dispatch=1`` on non-windowed planes is a no-op: those jobs
    are not fused-eligible, run their own (already batched or pipelined)
    dispatch paths, and match the flag-off oracle bit for bit."""
    solo_cfg = dataclasses.replace(cfg, fused_dispatch=0)
    n = 4 * WIN
    datasets = [_graph(seed, n) for seed in (3, 5)]
    serial = [_cc_serial(solo_cfg, s, d) for s, d in datasets]
    with JobManager() as jm:
        jobs = [
            jm.submit_aggregation(
                EdgeStream.from_arrays(s, d, cfg),
                ConnectedComponents(),
                name=f"{plane}-{i}",
            )
            for i, (s, d) in enumerate(datasets)
        ]
        outs = [_materialize_cc(job.results()) for job in jobs]
    for i, (want, got) in enumerate(zip(serial, outs)):
        _assert_windows_equal(want, got, f"{plane} job {i}")


def test_mixed_shape_cohorts_fuse_peers_and_solo_loners():
    """Three shape/descriptor classes in one fused manager: the 1024-edge
    CC jobs may fuse with each other only; the 512-edge CC job and the
    EdgeCount job have no same-key peers and must solo — all four streams
    bit-identical to their oracles."""
    big = [_graph(seed, 8 * WIN) for seed in (11, 13, 17)]
    small_cfg_solo = dataclasses.replace(CFG_SOLO, ingest_window_edges=512)
    small_cfg = dataclasses.replace(small_cfg_solo, fused_dispatch=1)
    small = _graph(19, 8 * 512)
    count = _graph(23, 8 * WIN)
    want_big = [_cc_serial(CFG_SOLO, s, d) for s, d in big]
    want_small = _cc_serial(small_cfg_solo, *small)
    want_count = [
        rec
        for rec in EdgeStream.from_arrays(*count, CFG_SOLO).aggregate(
            EdgeCount()
        )
    ]
    metrics.reset_fused_dispatch_stats()
    with JobManager() as jm:
        big_jobs = [
            jm.submit_aggregation(
                EdgeStream.from_arrays(s, d, CFG_FUSED),
                ConnectedComponents(),
                name=f"big-{i}",
            )
            for i, (s, d) in enumerate(big)
        ]
        small_job = jm.submit_aggregation(
            EdgeStream.from_arrays(*small, small_cfg),
            ConnectedComponents(),
            name="small",
        )
        count_job = jm.submit_aggregation(
            EdgeStream.from_arrays(*count, CFG_FUSED),
            EdgeCount(),
            name="count",
        )
        got_big = [_materialize_cc(job.results()) for job in big_jobs]
        got_small = _materialize_cc(small_job.results())
        got_count = list(count_job.results())
    for i, (want, got) in enumerate(zip(want_big, got_big)):
        _assert_windows_equal(want, got, f"big {i}")
    _assert_windows_equal(want_small, got_small, "small")
    assert [int(r[0]) for r in want_count] == [int(r[0]) for r in got_count]
    stats = metrics.fused_dispatch_stats()
    # loners solo'd; a 512-edge or EdgeCount row inside a CC-1024 cohort
    # would have broken the parity assertions above
    assert stats["fused_solo_fallbacks"] >= 1, stats
    assert stats["fused_jobs_per_dispatch_hwm"] <= 3, stats


# ---------------------------------------------------------------------------
# isolation: slow sinks, cancel, pause/resume mid-cohort
# ---------------------------------------------------------------------------


def test_slow_sink_skips_only_its_own_rows():
    """A wedged sink stalls ITS job's windows (never collected into a
    cohort while its queue is full) while fused peers complete with
    bit-identical output; releasing the sink completes the slow job with
    bit-identical output too — nothing was dropped with it."""
    slow_data = _graph(43, 8 * WIN)
    fast_data = [_graph(seed, 8 * WIN) for seed in (47, 53)]
    want_slow = _cc_serial(CFG_SOLO, *slow_data)
    want_fast = [_cc_serial(CFG_SOLO, s, d) for s, d in fast_data]
    gate = threading.Event()
    slow_records = []

    def slow_sink(rec):
        gate.wait(120)
        slow_records.append(rec)

    with JobManager(RuntimeConfig(job_queue_depth=2)) as jm:
        slow = jm.submit_aggregation(
            EdgeStream.from_arrays(*slow_data, CFG_FUSED),
            ConnectedComponents(),
            name="slow",
            sink=slow_sink,
        )
        fasts = [
            jm.submit_aggregation(
                EdgeStream.from_arrays(s, d, CFG_FUSED),
                ConnectedComponents(),
                name=f"fast-{i}",
            )
            for i, (s, d) in enumerate(fast_data)
        ]
        got_fast = [_materialize_cc(job.results()) for job in fasts]
        assert [j.state for j in fasts] == [JobState.DONE] * 2
        assert not slow.wait(0), "slow job should still be in flight"
        assert jm.status()["jobs"]["slow"]["job_queue_full_skips"] >= 1
        gate.set()
        assert slow.wait(60)
        assert slow.state == JobState.DONE
    for i, (want, got) in enumerate(zip(want_fast, got_fast)):
        _assert_windows_equal(want, got, f"fast {i}")
    _assert_windows_equal(want_slow, _materialize_cc(slow_records), "slow")


def test_cancel_mid_cohort_no_drop_no_duplicate():
    """Cancelling one cohort member mid-stream leaves its peers'
    emissions bit-identical and its own delivered records an exact PREFIX
    of the solo oracle — every delivered window exactly once, in order."""
    datasets = [_graph(seed, 16 * WIN) for seed in (61, 67, 71, 73)]
    serial = [_cc_serial(CFG_SOLO, s, d) for s, d in datasets]
    with JobManager() as jm:
        jobs = [
            jm.submit_aggregation(
                EdgeStream.from_arrays(s, d, CFG_FUSED),
                ConnectedComponents(),
                name=f"cc-{i}",
            )
            for i, (s, d) in enumerate(datasets)
        ]
        victim = jobs[0]
        it = victim.results()
        first = np.asarray(next(it)[0].parent)  # mid-stream, cohorts live
        victim.cancel(wait=True)
        rest = _materialize_cc(it)
        got_victim = [first] + rest
        got_peers = [_materialize_cc(job.results()) for job in jobs[1:]]
        assert victim.state == JobState.CANCELLED
    for i, (want, got) in enumerate(zip(serial[1:], got_peers)):
        _assert_windows_equal(want, got, f"peer {i}")
    assert len(got_victim) <= len(serial[0])
    _assert_windows_equal(
        serial[0][: len(got_victim)], got_victim, "victim prefix"
    )


def test_pause_resume_mid_cohort_parity():
    """Pausing a cohort member suspends its iterator in place; peers keep
    fusing among themselves; resume continues bit-exact (the FoldRequest
    protocol self-heals: a resume that reaches a parked yield via plain
    ``next()`` solo-folds instead of dropping the window)."""
    datasets = [_graph(seed, 8 * WIN) for seed in (79, 83, 89)]
    serial = [_cc_serial(CFG_SOLO, s, d) for s, d in datasets]
    with JobManager() as jm:
        jobs = [
            jm.submit_aggregation(
                EdgeStream.from_arrays(s, d, CFG_FUSED),
                ConnectedComponents(),
                name=f"cc-{i}",
            )
            for i, (s, d) in enumerate(datasets)
        ]
        paused = jobs[0]
        it = paused.results()
        first = np.asarray(next(it)[0].parent)
        paused.pause()
        got_peers = [_materialize_cc(job.results()) for job in jobs[1:]]
        assert paused.resume()
        got_paused = [first] + _materialize_cc(it)
        assert paused.state == JobState.DONE
    _assert_windows_equal(serial[0], got_paused, "paused job")
    for i, (want, got) in enumerate(zip(serial[1:], got_peers)):
        _assert_windows_equal(want, got, f"peer {i}")


# ---------------------------------------------------------------------------
# compile economy: pow2 row buckets across tenancy variation
# ---------------------------------------------------------------------------


def test_zero_recompiles_across_jobs_per_batch_1_to_16():
    """Once the solo executable and the pow2 row buckets are warm, tenancy
    varying 1 -> 16 jobs per dispatch compiles NOTHING: every cohort size
    buckets to a warmed row shape of the one shared executable."""
    cc = ConnectedComponents()
    # warm the solo/windowed chain (update + combine + transform)
    _cc_serial(CFG_FUSED, *_graph(97, 2 * WIN))
    # warm every row bucket a 1..16-job cohort can hit (singletons never
    # dispatch the vmapped executable — they solo — so buckets start at 2),
    # and the matching cohort-drain split executables
    fold = cc._superpane_fold_fn(CFG_FUSED, False)
    for rows in (2, 4, 8, 16):
        states = fold(
            jnp.zeros((rows, WIN), jnp.int32),
            jnp.zeros((rows, WIN), jnp.int32),
            None,
            jnp.zeros((rows, WIN), bool),
        )
        cc._superpane_split_fn(CFG_FUSED, rows)(states)
    compile_cache.reset_stats()
    for n_jobs in (1, 2, 4, 8, 16):
        datasets = [
            _graph(100 + n_jobs + seed, 2 * WIN) for seed in range(n_jobs)
        ]
        with JobManager(RuntimeConfig(max_jobs=n_jobs)) as jm:
            jobs = [
                jm.submit_aggregation(
                    EdgeStream.from_arrays(s, d, CFG_FUSED),
                    ConnectedComponents(),
                    name=f"t{n_jobs}-{i}",
                )
                for i, (s, d) in enumerate(datasets)
            ]
            for job in jobs:
                job.collect()
    stats = compile_cache.stats()
    assert stats["recompiles"] == 0, stats
    assert stats["compiles"] == 0, (
        "tenancy variation over warm buckets must not compile",
        stats,
    )


# ---------------------------------------------------------------------------
# the FoldRequest protocol itself
# ---------------------------------------------------------------------------


def test_run_fused_protocol_and_solo_fallback_oracle():
    """White-box: the cohort-member generator yields FoldRequests with the
    advertised padded layout, accepts ``send(None)`` as the solo-fallback
    signal, and a protocol-naive plain ``next()`` consumer still gets the
    correct emission (self-healing) — both bit-identical to run()."""
    s, d = _graph(101, 4 * WIN)
    want = _cc_serial(CFG_SOLO, s, d)
    cc = ConnectedComponents()
    gen = cc.run_fused(EdgeStream.from_arrays(s, d, CFG_FUSED))
    got = []
    req = next(gen)
    while True:
        assert type(req) is FoldRequest
        assert req.src.shape == (WIN,) and req.mask.all()
        assert req.edges == WIN
        token, cfg_key, has_val, e_pad = req.key
        assert token is type(cc) and e_pad == WIN and not has_val
        # alternate the two legal resume forms: explicit solo signal and
        # the protocol-naive plain next() (Python: send(None))
        if len(got) % 2 == 0:
            rec = gen.send(None)
        else:
            rec = next(gen)
        got.append(np.asarray(rec[0].parent))
        try:
            req = next(gen)
        except StopIteration:
            break
    _assert_windows_equal(want, got, "protocol")


def test_fused_dispatch_stats_exposed():
    """The satellite surfaces: metrics_snapshot carries the fused section
    and the Prometheus exposition renders its counters."""
    metrics.reset_fused_dispatch_stats()
    metrics.fused_add("fused_dispatches", 2)
    metrics.fused_add("fused_jobs_total", 7)
    metrics.fused_high_water("fused_jobs_per_dispatch_hwm", 4)
    snap = metrics.metrics_snapshot()
    assert snap["fused"]["fused_dispatches"] == 2
    assert snap["fused"]["fused_jobs_per_dispatch_mean"] == 3.5
    prom = metrics.render_prometheus(snap)
    assert "gelly_fused_dispatches 2" in prom
    assert "gelly_fused_jobs_per_dispatch_hwm 4" in prom
    metrics.reset_fused_dispatch_stats()
    assert metrics.fused_dispatch_stats()["fused_dispatches"] == 0


def test_fused_dispatch_config_validation():
    with pytest.raises(ValueError, match="fused_dispatch"):
        StreamConfig(vertex_capacity=CAP, fused_dispatch=2)

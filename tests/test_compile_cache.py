"""The AOT executable cache (core/compile_cache.py): entry identity,
hit/miss/compile counters, and the RETRACE GUARD — the CC hot loop must
compile at most once per bucketed shape, however many streams, descriptors,
or windows re-create their closures.
"""

import numpy as np

from gelly_streaming_tpu.core import compile_cache
from gelly_streaming_tpu.core.config import StreamConfig
from gelly_streaming_tpu.core.stream import EdgeStream
from gelly_streaming_tpu.library.connected_components import ConnectedComponents


def test_key_hit_returns_same_entry_and_counts():
    compile_cache.reset_stats()
    a = compile_cache.cached_jit(("tcc", "k1"), lambda: (lambda x: x + 1))
    b = compile_cache.cached_jit(("tcc", "k1"), lambda: (lambda x: x + 99))
    assert a is b  # key hit: the first build wins, the second never traces
    x = np.ones(4, np.float32)
    assert float(a(x)[0]) == 2.0
    s = compile_cache.stats()
    assert s["key_misses"] >= 1 and s["key_hits"] >= 1


def test_compile_counted_once_per_shape():
    compile_cache.reset_stats()
    f = compile_cache.cached_jit(("tcc", "shapes"), lambda: (lambda x: x * 2))
    for _ in range(5):
        f(np.ones(8, np.float32))
    f(np.ones(16, np.float32))
    assert f.compiles == 2  # one per distinct shape
    assert compile_cache.recompiles() == 0


def test_retrace_guard_cc_hot_loop_100_same_shape_windows():
    """100 same-shape running windows over the wire fast path, with the
    stream AND the descriptor re-created mid-run: zero recompiles."""
    rng = np.random.default_rng(11)
    src = rng.integers(0, 64, 100 * 64).astype(np.int32)
    dst = rng.integers(0, 64, 100 * 64).astype(np.int32)
    cfg = StreamConfig(
        vertex_capacity=64, batch_size=64, ingest_window_edges=64
    )

    def run():
        out = (
            EdgeStream.from_arrays(src, dst, cfg)
            .aggregate(ConnectedComponents())
            .collect()
        )
        assert len(out) == 100  # one record per same-shape window
        return out

    run()  # warmup: compiles land here
    compile_cache.reset_stats()
    run()  # fresh EdgeStream + fresh ConnectedComponents (class cache token)
    stats = compile_cache.stats()
    assert stats["compiles"] == 0, stats
    assert stats["recompiles"] == 0, stats
    assert stats["dispatch_hits"] >= 100


def test_retrace_guard_superbatched_cc():
    rng = np.random.default_rng(12)
    src = rng.integers(0, 64, 64 * 64).astype(np.int32)
    dst = rng.integers(0, 64, 64 * 64).astype(np.int32)
    cfg = StreamConfig(vertex_capacity=64, batch_size=64, superbatch=8)

    def run():
        return (
            EdgeStream.from_arrays(src, dst, cfg)
            .aggregate(ConnectedComponents())
            .collect()
        )

    run()
    compile_cache.reset_stats()
    run()
    stats = compile_cache.stats()
    assert stats["compiles"] == 0, stats
    assert stats["recompiles"] == 0, stats


def test_property_streams_share_executables_across_streams():
    """Re-created property streams (stable kernel keys) never retrace."""
    rng = np.random.default_rng(13)
    cfg = StreamConfig(vertex_capacity=32, batch_size=32)

    def degrees():
        src = rng.integers(0, 32, 128).astype(np.int32)
        dst = rng.integers(0, 32, 128).astype(np.int32)
        return (
            EdgeStream.from_arrays(src, dst, cfg).get_degrees().collect()
        )

    degrees()
    compile_cache.reset_stats()
    degrees()  # same shapes, fresh stream + fresh kernel closure
    stats = compile_cache.stats()
    assert stats["compiles"] == 0, stats


def test_stats_shape():
    s = compile_cache.stats()
    for key in (
        "entries",
        "key_hits",
        "key_misses",
        "compiles",
        "compile_time_s",
        "dispatch_hits",
        "recompiles",
    ):
        assert key in s

"""Incidence-routed sampling estimator on the 8-device mesh (VERDICT r1 #9).

The round-1 build collapsed IncidenceSamplingTriangleCount into the broadcast
kernel with an argued equivalence; this is the real topology: a host router
(EdgeSampleMapper analog) emits SampledEdge envelopes only to interested
lanes, lanes live sharded over the mesh, and broadcast/incidence share the
apply path — so the estimates must be IDENTICAL while the shipped envelope
volume differs.
"""

import numpy as np
import pytest

from gelly_streaming_tpu.core.config import StreamConfig
from gelly_streaming_tpu.core.stream import EdgeStream
from gelly_streaming_tpu.library.incidence_sampling import (
    IncidenceRouter,
    MeshSampledTriangleCount,
)
from gelly_streaming_tpu.utils.value_types import SampledEdge

CFG = StreamConfig(vertex_capacity=16, max_degree=16, batch_size=8)


def _complete_graph(n):
    return [(i, j) for i in range(n) for j in range(i + 1, n)]


def _stream():
    return EdgeStream.from_collection(_complete_graph(8), CFG, batch_size=8)


def test_incidence_matches_broadcast_with_less_comm():
    bcast = MeshSampledTriangleCount(64, mode="broadcast", seed=11)
    inc = MeshSampledTriangleCount(64, mode="incidence", seed=11)
    est_b = [e[0] for e in bcast.run(_stream())]
    est_i = [e[0] for e in inc.run(_stream())]
    # identical estimates by construction: an uninterested lane cannot change
    assert est_b == est_i
    assert est_i[-1] >= 0.0
    # ...but the incidence topology ships far fewer envelopes
    total_b = sum(bcast.comm_envelopes)
    total_i = sum(inc.comm_envelopes)
    assert total_b == 28 * 64  # every (edge, lane) pair
    assert 0 < total_i < total_b / 4


def test_mesh_estimate_positive_on_triangle_rich_graph():
    inc = MeshSampledTriangleCount(256, mode="incidence", seed=3)
    ests = [e[0] for e in inc.run(_stream())]
    assert ests[-1] > 0.0


def test_star_graph_estimates_zero_through_router():
    edges = [(0, i) for i in range(1, 10)]
    inc = MeshSampledTriangleCount(64, mode="incidence", seed=5)
    stream = EdgeStream.from_collection(edges, CFG, batch_size=4)
    ests = [e[0] for e in inc.run(stream)]
    assert ests[-1] == 0.0


def test_router_emits_sampled_edge_envelopes():
    router = IncidenceRouter(num_samplers=8, capacity=16, seed=1)
    src = np.array([1, 2], np.int64)
    dst = np.array([2, 3], np.int64)
    env = router.route(src, dst)
    # edge 1 (index 1): every lane flips a 1/1 coin -> all resample
    assert (env["idx"] == 1).sum() == 8
    assert env["resample"][env["idx"] == 1].all()
    records = router.envelopes(
        env, {1: (1, 2), 2: (2, 3)}, lanes_per_shard=4
    )
    assert all(isinstance(r, SampledEdge) for r in records)
    assert {r.subtask for r in records} <= {0, 1}
    first = [r for r in records if r.edge_count == 1][0]
    assert (first.src, first.dst, first.resample) == (1, 2, True)


def test_rejects_uneven_lane_split():
    with pytest.raises(ValueError):
        MeshSampledTriangleCount(10)  # 10 lanes over 8 shards


def test_router_chunking_matches_single_pass():
    """Chunked routing (bounded [m, s] intermediates) must equal one pass —
    the carry state hand-off between chunks is the risky part."""
    rng = np.random.default_rng(5)
    src = rng.integers(0, 16, 400).astype(np.int64)
    dst = rng.integers(0, 16, 400).astype(np.int64)
    mask = rng.random(400) < 0.9
    mask[64:128] = False  # a fully-masked chunk must not change dtypes
    one = IncidenceRouter(num_samplers=8, capacity=16, seed=3)
    env_one = one.route(src, dst, mask)

    chunked = IncidenceRouter(num_samplers=8, capacity=16, seed=3)
    chunked.chunk_elems = 64 * 8  # 64-edge chunks: the chunked branch runs
    env_chunks = chunked.route(src, dst, mask)
    for k in env_one:
        assert env_one[k].dtype == env_chunks[k].dtype, k
        np.testing.assert_array_equal(env_one[k], env_chunks[k])
    np.testing.assert_array_equal(one.edge_tab, chunked.edge_tab)
    np.testing.assert_array_equal(one.third, chunked.third)

"""The observability plane (ISSUE 9): per-window span tracing, the
bounded log-bucketed latency histograms, the flight recorder, and the
metrics/trace exposition surface.

The two regression pins the satellites name:

* percentile math — proper NEAREST-RANK (p50 of [1, 2] is 1; p100 is the
  max with no index clamp), exact-value tested on both the recorder shim
  and the histogram;
* zero-overhead off path — with ``trace_sample`` at its default (off),
  the windowed planes add no recompiles and their emissions are
  bit-identical with tracing on vs off.
"""

import numpy as np
import pytest

from gelly_streaming_tpu.core.config import StreamConfig
from gelly_streaming_tpu.utils import metrics, tracing


@pytest.fixture(autouse=True)
def _clean_registries():
    yield
    tracing.reset_tracing()
    metrics.reset_histograms()


# ---------------------------------------------------------------------------
# nearest-rank percentile math (the off-by-one satellite)


def test_nearest_rank_exact_values():
    assert tracing.nearest_rank([1.0, 2.0], 50) == 1.0
    assert tracing.nearest_rank([1.0, 2.0], 100) == 2.0  # no IndexError
    assert tracing.nearest_rank([1.0, 2.0], 0) == 1.0
    xs = [10.0, 20.0, 30.0, 40.0]
    assert tracing.nearest_rank(xs, 25) == 10.0
    assert tracing.nearest_rank(xs, 50) == 20.0
    assert tracing.nearest_rank(xs, 75) == 30.0
    assert tracing.nearest_rank(xs, 99) == 40.0
    assert tracing.nearest_rank(xs, 51) == 30.0  # rank ceil(2.04) = 3
    assert tracing.nearest_rank([], 50) == 0.0
    assert tracing.nearest_rank([7.0], 100) == 7.0


def test_recorder_percentile_nearest_rank():
    rec = metrics.WindowLatencyRecorder()
    rec.record(1.0)
    rec.record(2.0)
    # the old int(len*p/100) index returned 2 for p50 and needed a clamp
    # at p100; nearest-rank gives the rank-1 value and the exact max
    assert rec.percentile(50) == 1.0
    assert rec.percentile(100) == 2.0
    assert rec.p50_ms == 1.0


def test_recorder_is_bounded_and_feeds_histogram():
    rec = metrics.WindowLatencyRecorder(max_samples=64)
    for i in range(1000):
        rec.latencies_ms.append(float(i + 1))  # the legacy direct-append API
    # the raw window is bounded; the histogram kept every sample
    assert len(rec.latencies_ms) == 64
    assert rec.histogram.count == 1000
    # percentiles still work over the retained window (the newest 64)
    assert rec.percentile(100) == 1000.0
    # and window_closed/result_emitted still drive it
    rec2 = metrics.WindowLatencyRecorder()
    rec2.window_closed()
    rec2.result_emitted()
    assert len(rec2.latencies_ms) == 1
    assert rec2.histogram.count == 1


# ---------------------------------------------------------------------------
# the bounded histogram


def test_histogram_exact_quantiles_on_bucket_boundaries():
    h = tracing.LatencyHistogram()
    # 1.0 / 2.0 / 4.0 ms are exact bucket lower bounds (LO_MS = 2^-10),
    # so nearest-rank quantiles return them exactly
    for v in (1.0, 2.0, 4.0):
        h.record(v)
    assert h.quantile(0) == 1.0
    assert h.quantile(34) == 2.0  # rank ceil(1.02) = 2
    assert h.quantile(50) == 2.0
    assert h.quantile(67) == 4.0  # rank ceil(2.01) = 3
    assert h.quantile(100) == 4.0
    assert h.count == 3


def test_histogram_is_bounded_and_clamps():
    h = tracing.LatencyHistogram()
    for _ in range(10_000):
        h.record(1e12)  # way past the top bucket
        h.record(1e-4)  # below the bottom bucket
    snap = h.snapshot()
    assert snap["count"] == 20_000
    assert len(snap["buckets"]) == 2  # first and last bucket only
    assert snap["max_ms"] == 1e12  # exact extrema survive bucketing
    assert snap["min_ms"] == 1e-4
    # relative bucket error bound: a quantile is at most one bucket
    # (2^(1/8)) below the true value
    h2 = tracing.LatencyHistogram()
    h2.record(37.3)
    q = h2.quantile(50)
    assert q <= 37.3 < q * 2 ** (1 / tracing.LatencyHistogram.PER_OCTAVE)


def test_histogram_registry_scopes_and_eviction():
    metrics.reset_histograms()
    metrics.hist_record("window_close_to_emission_ms", 5.0, job="a/j1")
    metrics.hist_record("window_close_to_emission_ms", 7.0, job="a/j2")
    snap = metrics.hist_snapshot()
    assert snap["global"]["window_close_to_emission_ms"]["count"] == 2
    assert snap["jobs"]["a/j1"]["window_close_to_emission_ms"]["count"] == 1
    # thread-local job tagging
    metrics.set_hist_job("a/j1")
    try:
        metrics.hist_record("push_to_fold_ms", 1.0)
    finally:
        metrics.set_hist_job(None)
    assert metrics.hist_snapshot()["jobs"]["a/j1"]["push_to_fold_ms"][
        "count"
    ] == 1
    # job eviction drops the job rows, keeps the global scope
    metrics.drop_job_stats("a/j1")
    snap = metrics.hist_snapshot()
    assert "a/j1" not in snap["jobs"]
    assert snap["global"]["window_close_to_emission_ms"]["count"] == 2


# ---------------------------------------------------------------------------
# spans, sampling, the flight recorder


def test_flight_recorder_ring_keeps_last_capacity():
    rec = tracing.FlightRecorder(capacity=8)
    for i in range(20):
        span = tracing.WindowSpan(i + 1, "test", i)
        rec.record(span)
    spans = rec.last(100)
    assert len(spans) == 8
    assert [s["window"] for s in spans] == list(range(12, 20))  # oldest first
    assert rec.stats()["recorded"] == 20
    assert rec.stats()["held"] == 8
    rec.clear()
    assert rec.last(100) == []
    assert rec.stats()["recorded"] == 0


def test_span_stage_sum_equals_total():
    import time

    span = tracing.WindowSpan(1, "test", 0)
    t0 = time.perf_counter()
    time.sleep(0.002)
    span.mark("pack", t0)
    t1 = time.perf_counter()
    time.sleep(0.002)
    span.mark("dispatch", t1)
    entry = span.finish()
    total = sum(s["ms"] for s in entry["stages"])
    # the "queued" residual makes the stage sum the total by construction
    assert entry["stages"][-1]["stage"] == "queued"
    assert abs(total - entry["total_ms"]) < 0.01
    assert entry["total_ms"] >= 4.0


def test_sampler_stride_is_deterministic():
    cfg_on = StreamConfig(trace_sample=1.0)
    s = tracing.sampler(cfg_on, "t")
    assert all(s.begin(i) is not None for i in range(5))
    cfg_half = StreamConfig(trace_sample=0.5)
    s2 = tracing.sampler(cfg_half, "t")
    hits = [s2.begin(i) is not None for i in range(6)]
    assert hits == [True, False, True, False, True, False]
    cfg_off = StreamConfig()
    assert tracing.sampler(cfg_off, "t") is None


def test_resolve_sample_config_beats_env(monkeypatch):
    monkeypatch.setenv("GELLY_TRACE_SAMPLE", "0.25")
    assert tracing.resolve_sample(StreamConfig()) == 0.25
    assert tracing.resolve_sample(StreamConfig(trace_sample=1.0)) == 1.0
    monkeypatch.delenv("GELLY_TRACE_SAMPLE")
    assert tracing.resolve_sample(StreamConfig()) == 0.0
    monkeypatch.setenv("GELLY_TRACE_SAMPLE", "not-a-float")
    assert tracing.resolve_sample(StreamConfig()) == 0.0


def test_trace_sample_validation():
    with pytest.raises(ValueError):
        StreamConfig(trace_sample=1.5)
    with pytest.raises(ValueError):
        StreamConfig(trace_sample=-0.1)


def test_find_span_depth_limited():
    span = tracing.WindowSpan(1, "t", 0)
    assert tracing.find_span(span) is span
    assert tracing.find_span((("pane", "arenas", span), "dev")) is span
    assert tracing.find_span(("no", "span")) is None
    assert tracing.find_span(np.zeros(4)) is None


# ---------------------------------------------------------------------------
# end-to-end: the windowed planes


def _windowed_stream(cfg, src, dst, bs):
    from gelly_streaming_tpu.core.stream import EdgeStream
    from gelly_streaming_tpu.core.types import EdgeBatch

    def factory():
        for o in range(0, len(src), bs):
            yield EdgeBatch.from_arrays(src[o : o + bs], dst[o : o + bs], pad_to=bs)

    return EdgeStream.from_batches(factory, cfg)


def _run_cc(trace_sample, async_windows=0, n=1 << 13, cap=1 << 10, bs=512):
    from gelly_streaming_tpu.library.connected_components import (
        ConnectedComponents,
    )

    rng = np.random.default_rng(11)
    src = rng.integers(0, cap, n).astype(np.int32)
    dst = rng.integers(0, cap, n).astype(np.int32)
    cfg = StreamConfig(
        vertex_capacity=cap,
        batch_size=bs,
        ingest_window_edges=2 * bs,
        async_windows=async_windows,
        trace_sample=trace_sample,
    )
    recs = list(
        ConnectedComponents().run(_windowed_stream(cfg, src, dst, bs))
    )
    return [np.asarray(r[0].parent) for r in recs]


@pytest.mark.timeout_cap(300)
@pytest.mark.parametrize("depth", [0, 3])
def test_tracing_off_is_no_op_and_emissions_bit_identical(depth):
    """The overhead-regression satellite: trace_sample=0 leaves the
    flight recorder untouched and adds zero compiles, and a traced run's
    emissions are bit-identical to the untraced oracle's."""
    base = _run_cc(0.0, async_windows=depth)  # warmup: compiles land here
    recorded_before = tracing.span_stats()["recorded"]
    cc_before = metrics.compile_cache_stats()
    off = _run_cc(0.0, async_windows=depth)
    cc_mid = metrics.compile_cache_stats()
    assert tracing.span_stats()["recorded"] == recorded_before
    assert cc_mid["compiles"] == cc_before["compiles"]
    on = _run_cc(1.0, async_windows=depth)
    cc_after = metrics.compile_cache_stats()
    # tracing on: same executables (0 new compiles, 0 recompiles)...
    assert cc_after["compiles"] == cc_mid["compiles"]
    assert cc_after["recompiles"] == cc_mid["recompiles"]
    # ...and bit-identical emissions
    assert len(base) == len(off) == len(on)
    for a, b, c in zip(base, off, on):
        np.testing.assert_array_equal(a, b)
        np.testing.assert_array_equal(a, c)
    # the traced run actually recorded one span per window
    assert tracing.span_stats()["recorded"] - recorded_before == len(on)


@pytest.mark.timeout_cap(300)
def test_async_spans_cover_all_stages_and_sum_to_total():
    tracing.reset_tracing()
    out = _run_cc(1.0, async_windows=3)
    spans = tracing.flight_recorder().last(64)
    assert len(spans) == len(out)
    for span in spans:
        assert span["plane"] == "windowed"
        stages = {s["stage"] for s in span["stages"]}
        assert {"pack", "transfer", "dispatch", "drain", "emit", "queued"} <= stages
        total = sum(s["ms"] for s in span["stages"])
        # the queued residual makes this exact up to rounding
        assert abs(total - span["total_ms"]) <= 0.05 + 0.01 * len(
            span["stages"]
        )
    # trace ids are unique and monotonic in record order
    ids = [s["trace_id"] for s in spans]
    assert ids == sorted(ids) and len(set(ids)) == len(ids)


@pytest.mark.timeout_cap(300)
def test_sync_plane_spans_and_close_to_emission_histogram():
    tracing.reset_tracing()
    metrics.reset_histograms()
    out = _run_cc(1.0, async_windows=0)
    spans = tracing.flight_recorder().last(64)
    assert len(spans) == len(out)
    assert all(s["plane"] == "merge" for s in spans)
    hist = metrics.hist_snapshot()["global"]["window_close_to_emission_ms"]
    assert hist["count"] == len(out)
    assert hist["p99_ms"] >= hist["p50_ms"] > 0


@pytest.mark.timeout_cap(300)
def test_sampling_rate_traces_subset():
    tracing.reset_tracing()
    out = _run_cc(0.5, async_windows=0)
    spans = tracing.flight_recorder().last(64)
    assert len(spans) == (len(out) + 1) // 2
    windows = [s["window"] for s in spans]
    assert windows == sorted(windows)


# ---------------------------------------------------------------------------
# exposition: snapshot + Prometheus text format


def test_metrics_snapshot_shape_and_prometheus_render():
    metrics.reset_histograms()
    metrics.hist_record("sched_queue_wait_ms", 2.0, job="t/j")
    snap = metrics.metrics_snapshot()
    for key in (
        "pipeline",
        "comms",
        "wire",
        "compile_cache",
        "jobs",
        "tenants",
        "histograms",
        "spans",
    ):
        assert key in snap
    text = metrics.render_prometheus(snap)
    lines = text.splitlines()
    # samples are gelly_-prefixed; HELP/TYPE metadata lines ride above
    # each family (the strict-format contract tests/test_prometheus_lint
    # pins in full)
    assert all(
        l.startswith(("gelly_", "# HELP gelly_", "# TYPE gelly_"))
        for l in lines
        if l
    )
    # histogram series: cumulative buckets end at +Inf == count
    inf = [l for l in lines if 'le="+Inf"' in l and "sched_queue_wait" in l]
    assert inf and inf[0].endswith(" 1")
    assert any(l.startswith("gelly_sched_queue_wait_ms_count") for l in lines)
    # JSON-serializable end to end (the metrics verb ships it as JSON)
    import json

    json.dumps(snap)

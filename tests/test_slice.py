"""slice() + neighborhood aggregation tests mirroring TestSlice.java goldens.

All nine combinations of {fold, reduce, apply} x {OUT(default), IN, ALL}
(TestSlice.java:40-201), with the same user functions: SumEdgeValues fold,
SumEdgeValuesReduce, and the big/small SumEdgeValuesApply."""

import jax.numpy as jnp
import pytest

from gelly_streaming_tpu.core.types import EdgeDirection

from fixtures import assert_lines, long_long_stream

FOLD_OUT = "1,25\n2,23\n3,69\n4,45\n5,51"
FOLD_IN = "1,51\n2,12\n3,36\n4,34\n5,80"
FOLD_ALL = "1,76\n2,35\n3,105\n4,79\n5,131"
APPLY_OUT = "1,small\n2,small\n3,big\n4,small\n5,big"
APPLY_IN = "1,big\n2,small\n3,small\n4,small\n5,big"
APPLY_ALL = "1,big\n2,small\n3,big\n4,big\n5,big"


def _fold(accum, vid, nbr, val):
    # SumEdgeValues (TestSlice.java:206-214): accum = (vertex id, sum + val)
    return (vid, accum[1] + val)


def _reduce(a, b):
    return a + b


def _apply(vid, nbrs, vals, valid):
    # SumEdgeValuesApply (TestSlice.java:221-238): sum > 50 -> "big" else "small"
    s = jnp.sum(jnp.where(valid, vals, 0))
    return (vid, s > 50)


def _post(rec):
    vid, big = rec
    return (vid, "big" if big else "small")


@pytest.mark.parametrize(
    "direction,golden",
    [
        (EdgeDirection.OUT, FOLD_OUT),
        (EdgeDirection.IN, FOLD_IN),
        (EdgeDirection.ALL, FOLD_ALL),
    ],
)
def test_fold_neighbors(direction, golden):
    out = long_long_stream().slice(1000, direction).fold_neighbors((0, 0), _fold)
    assert_lines(out.lines(), golden)


@pytest.mark.parametrize(
    "direction,golden",
    [
        (EdgeDirection.OUT, FOLD_OUT),
        (EdgeDirection.IN, FOLD_IN),
        (EdgeDirection.ALL, FOLD_ALL),
    ],
)
def test_reduce_on_edges(direction, golden):
    out = long_long_stream().slice(1000, direction).reduce_on_edges(_reduce)
    assert_lines(out.lines(), golden)


@pytest.mark.parametrize(
    "direction,golden",
    [
        (EdgeDirection.OUT, APPLY_OUT),
        (EdgeDirection.IN, APPLY_IN),
        (EdgeDirection.ALL, APPLY_ALL),
    ],
)
def test_apply_on_neighbors(direction, golden):
    out = (
        long_long_stream()
        .slice(1000, direction)
        .apply_on_neighbors(_apply, post=_post)
    )
    assert_lines(out.lines(), golden)


def test_slice_multi_batch_single_window():
    # Without timestamps the finite stream forms one pane regardless of batching.
    out = (
        long_long_stream(batch_size=2)
        .slice(1000, EdgeDirection.OUT)
        .reduce_on_edges(_reduce)
    )
    assert_lines(out.lines(), FOLD_OUT)


# ---------------------------------------------------------------------------
# Sharded path: all nine combinations again through the 8-device mesh
# (VERDICT r2 missing #5 — slice() is a distributed keyed window,
# SimpleEdgeStream.java:149-163)

from gelly_streaming_tpu.core.config import StreamConfig
from fixtures import LONG_LONG_EDGES
from gelly_streaming_tpu.core.stream import EdgeStream

SHARDED_CFG = StreamConfig(
    vertex_capacity=16, max_degree=16, batch_size=4, num_shards=8
)


def _sharded_stream():
    return EdgeStream.from_collection(LONG_LONG_EDGES, SHARDED_CFG, batch_size=4)


@pytest.mark.parametrize(
    "direction,golden",
    [
        (EdgeDirection.OUT, FOLD_OUT),
        (EdgeDirection.IN, FOLD_IN),
        (EdgeDirection.ALL, FOLD_ALL),
    ],
)
def test_fold_neighbors_sharded(direction, golden):
    out = _sharded_stream().slice(1000, direction).fold_neighbors((0, 0), _fold)
    assert_lines(out.lines(), golden)


@pytest.mark.parametrize(
    "direction,golden",
    [
        (EdgeDirection.OUT, FOLD_OUT),
        (EdgeDirection.IN, FOLD_IN),
        (EdgeDirection.ALL, FOLD_ALL),
    ],
)
def test_reduce_on_edges_sharded(direction, golden):
    out = _sharded_stream().slice(1000, direction).reduce_on_edges(_reduce)
    assert_lines(out.lines(), golden)


@pytest.mark.parametrize(
    "direction,golden",
    [
        (EdgeDirection.OUT, APPLY_OUT),
        (EdgeDirection.IN, APPLY_IN),
        (EdgeDirection.ALL, APPLY_ALL),
    ],
)
def test_apply_on_neighbors_sharded(direction, golden):
    out = (
        _sharded_stream()
        .slice(1000, direction)
        .apply_on_neighbors(_apply, post=_post)
    )
    assert_lines(out.lines(), golden)


# ---------------------------------------------------------------------------
# Randomized differential: sharded vs single-shard slice aggregations must
# agree on arbitrary streams, not just the 7-edge fixture (the goldens pin
# exactness; this pins breadth).

import numpy as np


@pytest.mark.parametrize("seed", range(4))
@pytest.mark.parametrize(
    "direction", [EdgeDirection.OUT, EdgeDirection.IN, EdgeDirection.ALL]
)
def test_slice_sharded_matches_single_random(seed, direction):
    rng = np.random.default_rng(seed)
    cap, deg, n = 32, 32, int(rng.integers(20, 120))
    edges = [
        (int(a), int(b), int(a) * 100 + int(b))
        for a, b in zip(
            rng.integers(0, cap, n), rng.integers(0, cap, n)
        )
    ]
    single = StreamConfig(vertex_capacity=cap, max_degree=deg, batch_size=8)
    sharded = StreamConfig(
        vertex_capacity=cap, max_degree=deg, batch_size=8, num_shards=8
    )

    def run(cfg):
        out = (
            EdgeStream.from_collection(edges, cfg, batch_size=8)
            .slice(1000, direction)
            .reduce_on_edges(_reduce)
        )
        return sorted(out.lines())

    assert run(sharded) == run(single), f"seed={seed} dir={direction}"


def test_apply_on_neighbors_host_escape_hatch():
    """SURVEY §7 / VERDICT r3 missing #3: mode='host' runs a plain-Python
    (non-traceable) closure per vertex over the lazy-neighbor analog —
    string building, the canonical thing a jax kernel cannot do.  Ref:
    SnapshotStream.java:143-172 (arbitrary Java over an Iterable)."""
    from gelly_streaming_tpu.core.types import EdgeDirection

    stream = long_long_stream()
    out = list(
        stream.slice(1000, EdgeDirection.OUT).apply_on_neighbors(
            lambda vid, neighbors: f"{vid}:"
            + "+".join(f"{nb}({val:g})" for nb, val in neighbors),
            mode="host",
        )
    )
    got = sorted(r[0] for r in out)
    assert got == [
        "1:2(12)+3(13)",
        "2:3(23)",
        "3:4(34)+5(35)",
        "4:5(45)",
        "5:1(51)",
    ]


def test_apply_on_neighbors_host_collector_and_valueless():
    """Host mode supports 0..n emissions per vertex (the reference's
    Collector) and value-less streams pass val=None per neighbor."""
    import numpy as np

    from gelly_streaming_tpu.core.config import StreamConfig
    from gelly_streaming_tpu.core.stream import EdgeStream
    from gelly_streaming_tpu.core.types import EdgeDirection

    cfg = StreamConfig(vertex_capacity=16, batch_size=8)
    src = np.array([1, 1, 2], np.int32)
    dst = np.array([2, 3, 3], np.int32)

    def wedges(vid, neighbors):
        assert all(v is None for _, v in neighbors)
        ids = [nb for nb, _ in neighbors]
        return [(vid, a, b) for a in ids for b in ids if a < b]

    out = list(
        EdgeStream.from_arrays(src, dst, cfg)
        .slice(1000, EdgeDirection.OUT)
        .apply_on_neighbors(wedges, mode="host")
    )
    assert out == [(1, 2, 3)]

    import pytest

    with pytest.raises(ValueError, match="unknown apply_on_neighbors mode"):
        EdgeStream.from_arrays(src, dst, cfg).slice(
            1000, EdgeDirection.OUT
        ).apply_on_neighbors(wedges, mode="python")


def test_fold_and_reduce_host_modes():
    """EdgesFold/EdgesReduce escape hatches: plain-Python accumulators
    (string building) and reducers through slice(), mirroring the
    reference's arbitrary-Java contract (EdgesFold.java:47,
    EdgesReduce.java:43)."""
    from gelly_streaming_tpu.core.types import EdgeDirection

    stream = long_long_stream()
    folded = sorted(
        r[0]
        for r in stream.slice(1000, EdgeDirection.OUT).fold_neighbors(
            "", lambda acc, vid, nbr, val: acc + f"[{vid}->{nbr}:{val:g}]",
            mode="host",
        )
    )
    assert folded == [
        "[1->2:12][1->3:13]",
        "[2->3:23]",
        "[3->4:34][3->5:35]",
        "[4->5:45]",
        "[5->1:51]",
    ]

    reduced = sorted(
        tuple(r)
        for r in long_long_stream()
        .slice(1000, EdgeDirection.OUT)
        .reduce_on_edges(lambda a, b: max(a, b), mode="host")
    )
    # device-path golden for comparison (same reduce, traceable form)
    import jax.numpy as jnp

    dev = sorted(
        tuple(r)
        for r in long_long_stream()
        .slice(1000, EdgeDirection.OUT)
        .reduce_on_edges(lambda a, b: jnp.maximum(a, b))
    )
    assert [(int(k), float(v)) for k, v in reduced] == [
        (int(k), float(v)) for k, v in dev
    ]

    import pytest

    with pytest.raises(ValueError, match="unknown fold_neighbors mode"):
        stream.slice(1000, EdgeDirection.OUT).fold_neighbors(
            "", lambda *a: "", mode="python"
        )


def test_fold_neighbors_host_list_accumulator_is_one_record():
    """A list-valued accumulator must emit as ONE record per vertex, not
    splat through the host-apply collector convention (verify-drive
    finding)."""
    from gelly_streaming_tpu.core.config import StreamConfig
    from gelly_streaming_tpu.core.stream import EdgeStream
    from gelly_streaming_tpu.core.types import EdgeDirection

    cfg = StreamConfig(vertex_capacity=16, batch_size=8)
    s = EdgeStream.from_collection(
        [(1, 2, 12.0), (1, 3, 13.0), (2, 3, 23.0)], cfg
    )
    out = sorted(
        r[0]
        for r in s.slice(1000, EdgeDirection.OUT).fold_neighbors(
            [], lambda acc, vid, nbr, val: acc + [nbr], mode="host"
        )
    )
    assert out == [[2, 3], [3]]


def test_fold_neighbors_host_tuple_accumulator_matches_device_arity():
    """Tuple accumulators splat into multi-field records in BOTH modes
    (review finding: host mode must not change record arity)."""
    import jax.numpy as jnp

    from gelly_streaming_tpu.core.types import EdgeDirection

    dev = sorted(
        tuple(map(float, r))
        for r in long_long_stream()
        .slice(1000, EdgeDirection.OUT)
        .fold_neighbors(
            (jnp.float32(0), jnp.float32(0)),
            lambda acc, vid, nbr, val: (acc[0] + val, acc[1] + 1),
        )
    )
    host = sorted(
        tuple(map(float, r))
        for r in long_long_stream()
        .slice(1000, EdgeDirection.OUT)
        .fold_neighbors(
            (0.0, 0.0),
            lambda acc, vid, nbr, val: (acc[0] + val, acc[1] + 1),
            mode="host",
        )
    )
    assert host == dev
    assert all(len(r) == 2 for r in host)

"""Windowed PageRank (beyond the reference library): per-window ranks match
a host power iteration, dangling mass redistributes, sliding windows
compose, and ranks sum to 1 within each window."""

import numpy as np
import pytest

from gelly_streaming_tpu.core.config import StreamConfig
from gelly_streaming_tpu.core.stream import EdgeStream
from gelly_streaming_tpu.library.pagerank import pagerank_windows, windowed_pagerank


def _host_pagerank(edges, damping=0.85, iters=200):
    verts = sorted({v for e in edges for v in e})
    idx = {v: i for i, v in enumerate(verts)}
    n = len(verts)
    out_deg = np.zeros(n)
    for s, d in edges:
        out_deg[idx[s]] += 1
    r = np.full(n, 1.0 / n)
    for _ in range(iters):
        spread = np.zeros(n)
        for s, d in edges:
            spread[idx[d]] += r[idx[s]] / out_deg[idx[s]]
        dangling = r[out_deg == 0].sum() / n
        r = (1 - damping) / n + damping * (spread + dangling)
    return {v: r[idx[v]] for v in verts}


def _records(out):
    return {int(v): float(r) for v, r in out.collect()}


CFG = StreamConfig(vertex_capacity=32, max_degree=16, batch_size=8)


def test_single_window_matches_host_power_iteration():
    edges = [(1, 2), (2, 3), (3, 1), (3, 4), (4, 1), (5, 1)]
    stream = EdgeStream.from_collection(edges, CFG)
    got = _records(windowed_pagerank(stream, 1000, tol=1e-10))
    want = _host_pagerank(edges)
    assert set(got) == set(want)
    for v in want:
        assert abs(got[v] - want[v]) < 1e-5, (v, got[v], want[v])
    assert abs(sum(got.values()) - 1.0) < 1e-5


def test_dangling_vertices_keep_total_mass():
    # 3 has no out-edge: its mass must recirculate, not vanish
    edges = [(1, 2), (2, 3)]
    stream = EdgeStream.from_collection(edges, CFG)
    got = _records(windowed_pagerank(stream, 1000, tol=1e-10))
    want = _host_pagerank(edges)
    assert abs(sum(got.values()) - 1.0) < 1e-5
    for v in want:
        assert abs(got[v] - want[v]) < 1e-5


def test_rank_ordering_follows_structure():
    # hub 1 receives from everyone: top rank
    edges = [(2, 1), (3, 1), (4, 1), (1, 2)]
    stream = EdgeStream.from_collection(edges, CFG)
    got = _records(windowed_pagerank(stream, 1000))
    assert got[1] == max(got.values())


def test_sliding_windows_rank_per_window():
    timed = [
        (1, 2, 0.0, 100),
        (2, 1, 0.0, 200),
        (3, 4, 0.0, 1100),
        (4, 3, 0.0, 1200),
    ]
    stream = EdgeStream.from_collection(timed, CFG, batch_size=2, with_time=True)
    wins = list(pagerank_windows(stream, 2000, slide_ms=1000, tol=1e-10))
    # windows: 0:{p0} 1:{p0,p1} 2:{p1} — each sums to 1 over its own verts
    assert [sorted(v.tolist()) for v, _ in wins] == [
        [1, 2],
        [1, 2, 3, 4],
        [3, 4],
    ]
    for _, r in wins:
        assert abs(r.sum() - 1.0) < 1e-5
    # the symmetric 2-cycles make every vertex equal within its window
    np.testing.assert_allclose(wins[1][1], 0.25, atol=1e-5)


def test_windows_are_independent():
    # same subgraph in two windows -> identical ranks (no state bleed)
    timed = [(1, 2, 0.0, 100), (2, 1, 0.0, 200), (1, 2, 0.0, 1100), (2, 1, 0.0, 1200)]
    stream = EdgeStream.from_collection(timed, CFG, batch_size=2, with_time=True)
    wins = list(pagerank_windows(stream, 1000, tol=1e-10))
    assert len(wins) == 2
    np.testing.assert_allclose(wins[0][1], wins[1][1], atol=1e-7)


@pytest.mark.parametrize("seed", [0, 1])
def test_random_graph_matches_host(seed):
    rng = np.random.default_rng(seed)
    edges = list(
        {
            (int(rng.integers(0, 20)), int(rng.integers(0, 20)))
            for _ in range(40)
        }
    )
    edges = [e for e in edges if e[0] != e[1]]
    stream = EdgeStream.from_collection(edges, CFG)
    got = _records(windowed_pagerank(stream, 1000, tol=1e-12, max_iters=300))
    want = _host_pagerank(edges, iters=300)
    assert set(got) == set(want)
    for v in want:
        assert abs(got[v] - want[v]) < 1e-5

"""Async-vs-sync window pipeline equivalence (ISSUE 2).

``cfg.async_windows`` switches the windowed plane onto the asynchronous
pipeline (core/async_exec.py): pane packing on the prefetcher's pack thread,
overlapped transfers, non-blocking fold dispatch, and a completion queue
drained in window order.  The synchronous path (``async_windows=0``) is the
equivalence oracle: every test here runs both and asserts identical
emission sequences — plus restore/SIGKILL recovery parity, a retrace guard,
and the engine's own unit behaviors.

The threaded tests carry ``timeout_cap`` (tests/conftest.py): a hung
completion queue must fail the test, not wedge tier-1.
"""

import dataclasses
import os
import signal
import subprocess
import sys
import textwrap

import numpy as np
import pytest

from gelly_streaming_tpu.core import async_exec
from gelly_streaming_tpu.core.config import StreamConfig
from gelly_streaming_tpu.core.stream import EdgeStream
from gelly_streaming_tpu.core.types import EdgeBatch, EdgeDirection
from gelly_streaming_tpu.library.connected_components import ConnectedComponents
from gelly_streaming_tpu.library.triangles import window_triangles

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

CFG = StreamConfig(vertex_capacity=64, max_degree=16)
ASYNC = dataclasses.replace(CFG, async_windows=3)

pytestmark = pytest.mark.timeout_cap(300)


def _timed_edges(n=240, tmax=2400, seed=0, valued=False):
    rng = np.random.default_rng(seed)
    src = rng.integers(0, 64, n)
    dst = rng.integers(0, 64, n)
    t = np.sort(rng.integers(0, tmax, n))
    if valued:
        return [
            (int(a), int(b), float(a + b), int(ts))
            for a, b, ts in zip(src, dst, t)
        ]
    return [(int(a), int(b), 0, int(ts)) for a, b, ts in zip(src, dst, t)]


def _stream(cfg, edges, batch_size=16):
    return EdgeStream.from_collection(
        edges, cfg, batch_size=batch_size, with_time=True
    )


def _cc(cfg, edges, window_ms=100, **kw):
    return [
        str(r[0])
        for r in ConnectedComponents(window_ms=window_ms)
        .run(_stream(cfg, edges), **kw)
        .collect()
    ]


# ---------------------------------------------------------------------------
# emission-sequence equivalence
# ---------------------------------------------------------------------------


def test_event_time_windows_match_sync():
    edges = _timed_edges()
    sync = _cc(CFG, edges)
    assert sync == _cc(ASYNC, edges)
    assert len(sync) >= 10


def test_ingestion_pane_windows_match_sync():
    edges = [(a, b, 0, 0) for a, b, _v, _t in _timed_edges(n=200, seed=1)]
    untimed = [e[:3] for e in edges]

    def run(cfg):
        s = EdgeStream.from_collection(untimed, cfg, batch_size=16)
        return [str(r[0]) for r in ConnectedComponents().run(s).collect()]

    base = dataclasses.replace(CFG, ingest_window_edges=48)
    sync = run(base)
    assert sync == run(dataclasses.replace(base, async_windows=3))
    assert len(sync) >= 4


def test_empty_and_partial_windows_match_sync():
    # sparse timestamps: long gaps leave windows empty; singleton windows
    # exercise the 1-edge pad bucket
    edges = [
        (1, 2, 0, 10),
        (3, 4, 0, 950),  # window 9 after an 8-window gap
        (2, 3, 0, 2000),  # singleton window 20
        (5, 6, 0, 2010),
        (6, 7, 0, 5000),  # trailing singleton after another gap
    ]
    sync = _cc(CFG, edges)
    assert sync == _cc(ASYNC, edges)


def test_valued_stream_windows_match_sync():
    edges = _timed_edges(valued=True, seed=3)
    sync = _cc(CFG, edges)
    assert sync == _cc(ASYNC, edges)


def test_superbatch_async_matches_sync():
    edges = _timed_edges(seed=4)
    base = _cc(CFG, edges)
    sb = dataclasses.replace(CFG, superbatch=4)
    assert _cc(sb, edges) == base
    assert _cc(dataclasses.replace(sb, async_windows=3), edges) == base


def test_mesh_plane_async_matches_sync():
    edges = _timed_edges(seed=5)
    mesh = dataclasses.replace(CFG, num_shards=4)
    sync = _cc(mesh, edges, window_ms=200)
    assert sync == _cc(
        dataclasses.replace(mesh, async_windows=3), edges, window_ms=200
    )
    assert len(sync) >= 5


def test_late_records_routed_identically():
    # out-of-order stream with a bounded watermark: later-than-bound records
    # go to the late sink in both modes, and the pane emissions agree
    rng = np.random.default_rng(6)
    t = rng.integers(0, 1200, 200)
    edges = [
        (int(a), int(b), 0, int(ts))
        for a, b, ts in zip(
            rng.integers(0, 64, 200), rng.integers(0, 64, 200), t
        )
    ]
    base = dataclasses.replace(CFG, out_of_orderness_ms=150)

    def run(cfg):
        late = []

        def sink(src, dst, val, time):
            late.extend(
                (int(s), int(d), int(tt)) for s, d, tt in zip(src, dst, time)
            )

        stream = _stream(cfg, edges).on_late(sink)
        recs = [
            str(r[0])
            for r in ConnectedComponents(window_ms=100).run(stream).collect()
        ]
        return recs, late

    sync_recs, sync_late = run(base)
    async_recs, async_late = run(dataclasses.replace(base, async_windows=3))
    assert sync_recs == async_recs
    assert sync_late == async_late
    assert len(sync_late) > 0, "fixture must actually produce late records"


def test_window_triangles_async_matches_sync():
    edges = _timed_edges(n=300, seed=7)
    sync = window_triangles(_stream(CFG, edges), 200).collect()
    assert sync == window_triangles(_stream(ASYNC, edges), 200).collect()
    assert any(c > 0 for c, _ in sync)


def test_sliding_window_triangles_async_matches_sync():
    edges = _timed_edges(n=300, seed=8)
    sync = window_triangles(_stream(CFG, edges), 400, slide_ms=200).collect()
    assert (
        sync
        == window_triangles(_stream(ASYNC, edges), 400, slide_ms=200).collect()
    )


def test_snapshot_plane_async_matches_sync():
    edges = _timed_edges(n=200, seed=9, valued=True)
    sync = (
        _stream(CFG, edges)
        .slice(200, EdgeDirection.OUT)
        .reduce_on_edges(lambda a, b: a + b)
        .collect()
    )
    asyn = (
        _stream(ASYNC, edges)
        .slice(200, EdgeDirection.OUT)
        .reduce_on_edges(lambda a, b: a + b)
        .collect()
    )
    assert sync == asyn
    assert len(sync) > 20


def test_async_error_still_delivers_prior_windows():
    """A source failure mid-stream: windows closed before the failure are
    delivered (they were in the sequential path), then the error surfaces."""
    rng = np.random.default_rng(10)

    def make(cfg):
        def factory():
            for i in range(8):
                if i == 5:
                    raise RuntimeError("source died")
                yield EdgeBatch.from_arrays(
                    rng.integers(0, 64, 16).astype(np.int32),
                    rng.integers(0, 64, 16).astype(np.int32),
                    time=np.full(16, i * 100 + 50),
                )

        return EdgeStream.from_batches(factory, cfg)

    def run(cfg):
        recs = []
        with pytest.raises(RuntimeError, match="source died"):
            for r in ConnectedComponents(window_ms=100).run(make(cfg)):
                recs.append(str(r[0]))
        return recs

    rng = np.random.default_rng(10)
    sync = run(CFG)
    rng = np.random.default_rng(10)
    assert run(ASYNC) == sync
    assert len(sync) == 4  # windows 0..3 closed before batch 5's failure


# ---------------------------------------------------------------------------
# checkpoint/restore parity
# ---------------------------------------------------------------------------

EDGES_T = [
    (1, 2, 0, 10),
    (3, 4, 0, 110),
    (2, 3, 0, 210),
    (5, 6, 0, 310),
]


def test_checkpoint_file_matches_sync(tmp_path):
    """A full checkpointed run leaves a bit-identical final snapshot."""
    sync = _cc(CFG, EDGES_T, checkpoint_path=str(tmp_path / "s"))
    asyn = _cc(ASYNC, EDGES_T, checkpoint_path=str(tmp_path / "a"))
    assert sync == asyn
    zs = np.load(str(tmp_path / "s") + ".npz")
    za = np.load(str(tmp_path / "a") + ".npz")
    assert sorted(zs.files) == sorted(za.files)
    for k in zs.files:
        assert np.array_equal(zs[k], za[k]), k


def test_async_resumes_from_sync_snapshot(tmp_path):
    """Snapshots are cross-compatible: sync writes, async resumes (and the
    other way around) — both equal the uninterrupted run."""
    full = _cc(CFG, EDGES_T)
    ck1 = str(tmp_path / "x")
    _cc(CFG, EDGES_T[:2], checkpoint_path=ck1)
    resumed = _cc(ASYNC, EDGES_T[2:], checkpoint_path=ck1)
    assert resumed[-1] == full[-1]
    ck2 = str(tmp_path / "y")
    _cc(ASYNC, EDGES_T[:2], checkpoint_path=ck2)
    resumed2 = _cc(CFG, EDGES_T[2:], checkpoint_path=ck2)
    assert resumed2[-1] == full[-1]


_CHILD = textwrap.dedent(
    """
    import os, signal, sys
    os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=1"
    sys.path.insert(0, {repo!r})
    import jax
    jax.config.update("jax_platforms", "cpu")
    import numpy as np
    import jax.numpy as jnp
    from gelly_streaming_tpu.core.aggregation import SummaryBulkAggregation
    from gelly_streaming_tpu.core.config import StreamConfig
    from gelly_streaming_tpu.core.stream import EdgeStream

    class EdgeCount(SummaryBulkAggregation):
        # NON-idempotent fold: re-folding any pane after a resume would
        # overcount, so the final value proves exactly-once state
        def initial_state(self, cfg):
            return jnp.zeros((), jnp.int32)

        def update(self, state, src, dst, val, mask):
            return state + jnp.sum(mask.astype(jnp.int32))

        def combine(self, a, b):
            return a + b

    kill_after = int(os.environ.get("KILL_AFTER_SAVES", "0"))
    if kill_after:
        import gelly_streaming_tpu.utils.checkpoint as ckpt
        real = ckpt.save_state
        n = [0]
        def hooked(p, s):
            real(p, s)
            n[0] += 1
            if n[0] >= kill_after:
                os.kill(os.getpid(), signal.SIGKILL)  # no cleanup, no atexit
        ckpt.save_state = hooked

    rng = np.random.default_rng(5)
    src = rng.integers(0, 64, 1024).astype(np.int32)
    dst = rng.integers(0, 64, 1024).astype(np.int32)
    cfg = StreamConfig(
        vertex_capacity=64,
        batch_size=96,
        # 128 % 96 != 0 -> the WINDOWED runtime (not the wire fast path)
        ingest_window_edges=128,
        async_windows=int(os.environ.get("CHILD_ASYNC", "0")),
    )
    out = (
        EdgeStream.from_arrays(src, dst, cfg)
        .aggregate(EdgeCount(), checkpoint_path={ckpt_path!r})
        .collect()
    )
    print("WINDOWS", len(out), "FINAL", int(out[-1][0]))
    """
)


def _run_child(script, ckpt_path, env_extra):
    env = dict(os.environ, **env_extra)
    return subprocess.run(
        [sys.executable, str(script)], env=env, capture_output=True, timeout=300
    )


def test_sigkill_mid_stream_positions_match_sync(tmp_path):
    """SIGKILL the async windowed run mid-stream: the surviving snapshot's
    position/summary equal a sync run killed at the same save, and the async
    resume completes the non-idempotent count exactly."""
    ck_async = str(tmp_path / "ck_async")
    ck_sync = str(tmp_path / "ck_sync")
    s_async = tmp_path / "child_a.py"
    s_sync = tmp_path / "child_s.py"
    s_async.write_text(_CHILD.format(repo=REPO, ckpt_path=ck_async))
    s_sync.write_text(_CHILD.format(repo=REPO, ckpt_path=ck_sync))

    first = _run_child(
        s_async, ck_async, {"KILL_AFTER_SAVES": "3", "CHILD_ASYNC": "3"}
    )
    assert first.returncode == -signal.SIGKILL, (
        first.returncode,
        first.stdout,
        first.stderr,
    )
    ref = _run_child(
        s_sync, ck_sync, {"KILL_AFTER_SAVES": "3", "CHILD_ASYNC": "0"}
    )
    assert ref.returncode == -signal.SIGKILL

    za = np.load(ck_async + ".npz")
    zs = np.load(ck_sync + ".npz")
    assert sorted(za.files) == sorted(zs.files)
    for k in za.files:
        assert np.array_equal(za[k], zs[k]), (
            f"checkpoint field {k} diverged between async and sync kills"
        )

    # resume the async run from its snapshot: exact count, no re-fold
    second = _run_child(s_async, ck_async, {"CHILD_ASYNC": "3"})
    assert second.returncode == 0, second.stderr.decode()
    assert b"FINAL 1024" in second.stdout, second.stdout


# ---------------------------------------------------------------------------
# retrace guard + engine units
# ---------------------------------------------------------------------------


def test_async_windows_zero_recompiles():
    """Async mode preserves the executable-cache guarantee: a second run
    over same-shape windows mints zero recompiles."""
    from gelly_streaming_tpu.core import compile_cache

    edges = _timed_edges(n=320, tmax=2000, seed=11)

    def run():
        return _cc(ASYNC, edges)

    first = run()  # compiles land here
    compile_cache.reset_stats()
    assert run() == first
    assert compile_cache.stats()["recompiles"] == 0


def test_resolve_depth_precedence(monkeypatch):
    monkeypatch.delenv("GELLY_ASYNC_WINDOWS", raising=False)
    assert async_exec.resolve_depth(StreamConfig()) == 0
    monkeypatch.setenv("GELLY_ASYNC_WINDOWS", "5")
    assert async_exec.resolve_depth(StreamConfig()) == 5
    monkeypatch.setenv("GELLY_ASYNC_WINDOWS", "nonsense")
    assert async_exec.resolve_depth(StreamConfig()) == 0
    # explicit config wins over the env var
    monkeypatch.setenv("GELLY_ASYNC_WINDOWS", "5")
    assert async_exec.resolve_depth(ASYNC) == 3


def test_async_windows_validation():
    with pytest.raises(ValueError):
        StreamConfig(async_windows=-1)


def test_env_var_switches_pipeline_on(monkeypatch):
    """GELLY_ASYNC_WINDOWS alone (config untouched) runs the async plane
    with unchanged emissions."""
    edges = _timed_edges(seed=12)
    sync = _cc(CFG, edges)
    monkeypatch.setenv("GELLY_ASYNC_WINDOWS", "3")
    assert _cc(CFG, edges) == sync


def test_pipeline_metrics_populate():
    from gelly_streaming_tpu.utils import metrics

    edges = _timed_edges(seed=13)
    metrics.reset_pipeline_stats()
    _cc(ASYNC, edges)
    stats = metrics.pipeline_stats()
    assert stats["pipeline_windows_dispatched"] > 0
    assert (
        stats["pipeline_windows_drained"]
        == stats["pipeline_windows_dispatched"]
    )
    # depth 3 -> the completion queue must actually have filled past 1
    assert stats["pipeline_inflight_high_water"] >= 2
    metrics.reset_pipeline_stats()
    assert metrics.pipeline_stats()["pipeline_windows_dispatched"] == 0


def test_arena_pool_recycles_and_caps():
    pool = async_exec.ArenaPool(per_shape=2)
    a = pool.acquire((8,), np.int32)
    a[:] = 7
    pool.release(a)
    b = pool.acquire((8,), np.int32)
    assert b is a, "released arena must be recycled"
    assert not b.any(), "recycled arena must come back zeroed"
    c = pool.acquire((8,), np.int32)
    d = pool.acquire((8,), np.int32)
    pool.release(b, c, d)  # cap 2: one of the three is dropped
    assert len(pool._free[((8,), np.dtype(np.int32).str)]) == 2
    # different shape/dtype classes do not mix
    e = pool.acquire((8,), bool)
    assert e.dtype == bool


def test_arena_pool_never_blocks():
    """Regression: the pool must hand out fresh buffers past its retention
    cap instead of blocking — a blocking pool deadlocks the pack thread
    against the drain that would release arenas."""
    pool = async_exec.ArenaPool(per_shape=1)
    bufs = [pool.acquire((4,), np.int32) for _ in range(16)]
    assert len({id(b) for b in bufs}) == 16


def test_drain_waits_on_fold_output_not_record(monkeypatch):
    """Regression: the drain's arena-release wait must target the FOLD
    OUTPUT pytree.  CC's transform wraps state in a DisjointSet — not a
    registered pytree — so ``wait_ready`` on the emission record sees one
    opaque leaf and silently waits on nothing, recycling the window's
    arenas under a still-pending zero-copy fold (the corrupted-parents
    flake in test_runtime's four-jobs async parity)."""
    import jax

    waited = []
    real = async_exec.wait_ready

    def spy(tree):
        waited.append(tree)
        real(tree)

    monkeypatch.setattr(async_exec, "wait_ready", spy)
    # batch misaligned to the window so the stream rides the windowed
    # (arena-backed) plane, not the packed-wire fast path
    cfg = dataclasses.replace(
        StreamConfig(vertex_capacity=64, batch_size=24, ingest_window_edges=32),
        async_windows=2,
    )
    rng = np.random.default_rng(5)
    src = rng.integers(0, 64, 256).astype(np.int32)
    dst = rng.integers(0, 64, 256).astype(np.int32)
    recs = list(
        EdgeStream.from_arrays(src, dst, cfg).aggregate(ConnectedComponents())
    )
    assert recs
    assert waited, "drain released arenas without waiting on anything"
    for tree in waited:
        leaves = jax.tree.leaves(tree)
        assert leaves, "wait target flattened to nothing"
        assert all(
            hasattr(leaf, "block_until_ready") for leaf in leaves
        ), f"wait target has un-waitable leaves: {tree!r}"

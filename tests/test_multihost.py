"""Multi-host watermark agreement: pane closes gated on the slowest host.

The reference gets this from Flink's min-watermark propagation (a window fires
only when every input channel's watermark passed its end); here the ingest
hosts agree through a watermark board (parallel/multihost.py).  The tests run
N ingest threads in one process — the MiniCluster analog — and assert the
straggler-safety, share-alignment, and determinism properties the protocol
must provide, for both the async-board and lockstep-collective transports.
"""

import threading
import time as _time

import numpy as np
import pytest

from gelly_streaming_tpu.core.types import EdgeBatch
from gelly_streaming_tpu.parallel import multihost as mh

# every test here drives ingest threads through the watermark board; a
# wedged collective must fail the test, not the tier-1 run (the
# test-discipline analyzer pass gates this)
pytestmark = pytest.mark.timeout_cap(300)


def _batches(edges, batch_size=4):
    """[(src, dst, t), ...] -> EdgeBatch iterator with event time."""
    for i in range(0, len(edges), batch_size):
        chunk = edges[i : i + batch_size]
        yield EdgeBatch.from_edges(
            [(s, d, 0.0, t) for (s, d, t) in chunk],
            pad_to=batch_size,
            with_time=True,
        )


def _host_edges(host_id, pane_ids, per_pane=3, window_ms=100):
    """Deterministic disjoint edge share per host: pane w gets vertices
    host_id*1000 + w*10 + k."""
    out = []
    for w in pane_ids:
        for k in range(per_pane):
            v = host_id * 1000 + w * 10 + k
            out.append((v, v + 1, w * window_ms + 5 + k))
    return out


def _run_hosts(host_pane_ids, window_ms=100, delays=None):
    """Run one ingest thread per host; returns per-host closed WindowPanes."""
    num_hosts = len(host_pane_ids)
    board = mh.ProcessWatermarkBoard(num_hosts)
    results = {h: [] for h in range(num_hosts)}
    errors = []

    def work(h):
        try:
            delay = (delays or {}).get(h, 0.0)
            edges = _host_edges(h, host_pane_ids[h], window_ms=window_ms)

            def delayed():
                for b in _batches(edges):
                    if delay:
                        _time.sleep(delay)
                    yield b

            for pane in mh.multihost_tumbling_windows(
                delayed(), window_ms, h, board, timeout=30.0
            ):
                results[h].append(pane)
        except BaseException as e:  # surfaced in the main thread
            errors.append(e)

    threads = [threading.Thread(target=work, args=(h,)) for h in range(num_hosts)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=60.0)
    assert not any(t.is_alive() for t in threads), "ingest thread hung"
    if errors:
        raise errors[0]
    return results


@pytest.mark.parametrize("num_hosts", [2, 3])
def test_all_hosts_close_same_panes_in_order(num_hosts):
    results = _run_hosts([range(4)] * num_hosts)
    for h, panes in results.items():
        assert [p.window_id for p in panes] == [0, 1, 2, 3]
        for p in panes:
            # each host's share holds exactly its own edges for that pane
            assert p.num_edges == 3
            assert all(v // 1000 == h for v in p.src)
            assert all((v % 1000) // 10 == p.window_id for v in p.src)


def test_empty_shares_keep_pane_sequences_aligned():
    """A host with gaps in its panes still emits the full pane-id sequence
    (empty shares), so positional pairing across hosts stays correct."""
    results = _run_hosts([[0, 1, 2, 3], [0, 3]])
    assert [p.window_id for p in results[0]] == [0, 1, 2, 3]
    assert [p.window_id for p in results[1]] == [0, 1, 2, 3]
    assert [p.num_edges for p in results[1]] == [3, 0, 0, 3]


def test_straggler_holds_back_closes():
    """A slow host must delay everyone's pane closes (no early firing)."""
    board = mh.ProcessWatermarkBoard(2)
    fast_closed = []

    def fast():
        for pane in mh.multihost_tumbling_windows(
            _batches(_host_edges(0, range(3))), 100, 0, board, timeout=30.0
        ):
            fast_closed.append((pane.window_id, _time.monotonic()))

    t = threading.Thread(target=fast)
    t.start()
    _time.sleep(0.3)
    # the fast host has consumed its whole stream, but host 1 has not reported:
    # nothing may have closed yet
    assert fast_closed == []
    t_release = _time.monotonic()
    for pane in mh.multihost_tumbling_windows(
        _batches(_host_edges(1, range(3))), 100, 1, board, timeout=30.0
    ):
        pass
    t.join(timeout=30.0)
    assert not t.is_alive()
    assert [w for w, _ in fast_closed] == [0, 1, 2]
    assert all(ts >= t_release for _, ts in fast_closed)


def test_out_of_order_batch_does_not_regress_watermark():
    """A batch whose max time is below the host watermark must not crash or
    deadlock peers (the watermark clamps, matching the single-host path)."""
    results = _run_hosts(
        [[2, 1, 0, 3], [0, 1, 2, 3]],  # host 0 ingests panes out of order
        delays={1: 0.01},
    )
    # host 0's early watermark=2 means panes 0,1 may close before their edges
    # arrive; those arrivals are dropped as late, never corrupting the
    # sequence alignment
    assert [p.window_id for p in results[0]] == [0, 1, 2, 3]
    assert [p.window_id for p in results[1]] == [0, 1, 2, 3]


def test_late_edges_dropped_with_hook():
    board = mh.ProcessWatermarkBoard(1)
    late = []
    edges = _host_edges(0, [2]) + _host_edges(0, [0]) + _host_edges(0, [3])
    panes = list(
        mh.multihost_tumbling_windows(
            _batches(edges, batch_size=3),
            100,
            0,
            board,
            timeout=10.0,
            on_late=lambda wid, n: late.append((wid, n)),
        )
    )
    # single host: watermark hits 2 after the first batch; pane-0 edges in the
    # second batch are behind the watermark but pane 0 has NOT closed yet
    # (closes need watermark > pane id via a later batch), so whether they are
    # late depends on when pane 0 closed
    assert [p.window_id for p in panes] == [0, 1, 2, 3]
    total_emitted = sum(p.num_edges for p in panes)
    total_late = sum(n for _, n in late)
    assert total_emitted + total_late == 9


def test_crashing_host_releases_peers():
    """A host whose source raises must still report END (finally), so peers
    finish instead of deadlocking in wait_global."""
    board = mh.ProcessWatermarkBoard(2)
    peer_panes = []
    errors = []

    def failing_source():
        yield from _batches(_host_edges(0, [0]))
        raise IOError("source died")

    def crasher():
        try:
            for _ in mh.multihost_tumbling_windows(
                failing_source(), 100, 0, board, timeout=10.0
            ):
                pass
        except IOError:
            pass
        except BaseException as e:
            errors.append(e)

    def peer():
        try:
            for pane in mh.multihost_tumbling_windows(
                _batches(_host_edges(1, [0, 1])), 100, 1, board, timeout=10.0
            ):
                peer_panes.append(pane.window_id)
        except BaseException as e:
            errors.append(e)

    threads = [threading.Thread(target=crasher), threading.Thread(target=peer)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=30.0)
    assert not any(t.is_alive() for t in threads), "peer deadlocked"
    assert not errors
    assert peer_panes == [0, 1]


def test_empty_share_carries_value_structure():
    """Empty shares of a valued stream keep zero-length value arrays."""
    board = mh.ProcessWatermarkBoard(1)

    def batches():
        yield EdgeBatch.from_edges(
            [(1, 2, 7.5, 10), (3, 4, 2.5, 210)], pad_to=2, with_time=True
        )

    panes = list(
        mh.multihost_tumbling_windows(batches(), 100, 0, board, timeout=10.0)
    )
    assert [p.window_id for p in panes] == [0, 1, 2]
    middle = panes[1]
    assert middle.num_edges == 0
    assert middle.val is not None and len(np.asarray(middle.val)) == 0
    assert middle.time is not None and len(middle.time) == 0


def test_watermark_board_rejects_regression():
    board = mh.ProcessWatermarkBoard(2)
    board.report(0, 5)
    with pytest.raises(ValueError):
        board.report(0, 3)


def test_requires_event_time():
    board = mh.ProcessWatermarkBoard(1)
    batches = [
        EdgeBatch.from_edges([(1, 2), (3, 4)], pad_to=2, with_time=False)
    ]
    with pytest.raises(ValueError, match="event timestamps"):
        list(mh.multihost_tumbling_windows(iter(batches), 100, 0, board))


# ---------------------------------------------------------------------------
# lockstep (collective) transport
# ---------------------------------------------------------------------------


class _BarrierAllgather:
    """Thread-barrier allgather with the semantics of process_allgather."""

    def __init__(self, num_hosts):
        self._n = num_hosts
        self._vals = [None] * num_hosts
        self._barrier = threading.Barrier(num_hosts)
        self._tls = threading.local()

    def bind(self, host_id):
        self._tls.host_id = host_id
        return self._call

    def _call(self, local):
        self._vals[self._tls.host_id] = int(local)
        self._barrier.wait(timeout=30.0)
        out = np.array(self._vals, np.int64)
        self._barrier.wait(timeout=30.0)  # protect _vals from the next round
        return out


def _run_lockstep(host_pane_ids, window_ms=100):
    num_hosts = len(host_pane_ids)
    ag = _BarrierAllgather(num_hosts)
    results = {h: [] for h in range(num_hosts)}
    errors = []

    def work(h):
        try:
            edges = _host_edges(h, host_pane_ids[h], window_ms=window_ms)
            for pane in mh.lockstep_tumbling_windows(
                _batches(edges), window_ms, ag.bind(h)
            ):
                results[h].append(pane)
        except BaseException as e:
            errors.append(e)

    threads = [threading.Thread(target=work, args=(h,)) for h in range(num_hosts)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=60.0)
    assert not any(t.is_alive() for t in threads), "lockstep thread hung"
    if errors:
        raise errors[0]
    return results


def test_lockstep_equal_streams():
    results = _run_lockstep([range(3)] * 2)
    for h in (0, 1):
        assert [p.window_id for p in results[h]] == [0, 1, 2]
        assert all(p.num_edges == 3 for p in results[h])


def test_lockstep_unequal_batch_counts():
    """A host with fewer batches END-pads the collective; sequences align."""
    results = _run_lockstep([[0, 1, 2, 3, 4], [1, 2]])
    assert [p.window_id for p in results[0]] == [0, 1, 2, 3, 4]
    assert [p.window_id for p in results[1]] == [0, 1, 2, 3, 4]
    assert [p.num_edges for p in results[1]] == [0, 3, 3, 0, 0]


def test_jax_board_single_process_identity():
    """process_allgather degenerates to a 1-vector in a single-process run."""
    board = mh.JaxWatermarkBoard()
    np.testing.assert_array_equal(board.allgather(7), np.array([7]))


def test_distributed_env_single_process():
    env = mh.distributed_env()
    assert env == mh.HostEnv(0, 1)


def test_lockstep_collective_timeout_fails_fast():
    """A wedged peer must surface as TimeoutError, not an eternal hang
    (ADVICE r1: lockstep path had no deadline)."""
    import threading

    import pytest

    from gelly_streaming_tpu.core.types import EdgeBatch
    from gelly_streaming_tpu.parallel.multihost import lockstep_tumbling_windows

    hang = threading.Event()

    def wedged_allgather(mark):
        hang.wait(30)  # simulates a crashed peer never joining the round
        return np.array([mark])

    batches = [
        EdgeBatch.from_arrays(
            np.array([1], np.int32), np.array([2], np.int32),
            time=np.array([10], np.int64),
        )
    ]
    with pytest.raises(TimeoutError):
        list(
            lockstep_tumbling_windows(
                iter(batches), 100, wedged_allgather, timeout=0.2
            )
        )
    hang.set()


def test_deadline_runner_timeout_and_fresh_worker():
    """A wedged collective times out; the poisoned worker is abandoned (it
    may never return) and a FRESH daemon worker serves subsequent calls —
    the post-timeout behavior VERDICT r2 weak #7 flagged as untested."""
    import threading
    import time

    from gelly_streaming_tpu.parallel.multihost import _DeadlineRunner

    runner = _DeadlineRunner()
    release = threading.Event()

    def wedged(arg):
        release.wait(60.0)  # simulates a collective blocked on a dead peer
        return ("late", arg)

    with pytest.raises(TimeoutError):
        runner.run(wedged, 1, timeout=0.2)

    # the replacement worker answers normally...
    assert runner.run(lambda a: a * 2, 21, timeout=5.0) == 42
    # ...and exceptions from the worker surface on the caller
    def boom(_):
        raise RuntimeError("transport exploded")

    with pytest.raises(RuntimeError, match="transport exploded"):
        runner.run(boom, 0, timeout=5.0)

    # when the abandoned worker finally unblocks, its stale answer lands in
    # the ORPHANED channel — the live runner must not see it
    release.set()
    time.sleep(0.3)
    assert runner.run(lambda a: a + 1, 1, timeout=5.0) == 2
    # daemon worker threads: an exiting process is never blocked on them
    names = [t.name for t in threading.enumerate() if "watermark" in t.name]
    assert all(
        t.daemon for t in threading.enumerate() if "watermark" in t.name
    ), names


def test_multihost_panes_feed_mesh_aggregation():
    """The composed deployment shape: multi-host gated windows (DCN time
    plane) merged across hosts and folded by the MeshAggregationRunner (ICI
    data plane) — emissions must equal a single-host run over the union of
    the hosts' edges."""
    from gelly_streaming_tpu.core.aggregation import MeshAggregationRunner
    from gelly_streaming_tpu.core.config import StreamConfig
    from gelly_streaming_tpu.core.stream import EdgeStream
    from gelly_streaming_tpu.library.connected_components import ConnectedComponents

    window_ms = 100
    cfg = StreamConfig(vertex_capacity=64, batch_size=4, window_ms=window_ms)
    host_edges = {
        0: [(1, 2, 5), (2, 3, 15), (5, 6, 105)],
        1: [(3, 4, 8), (7, 8, 110), (6, 7, 115)],
    }

    def gathered_panes():
        board = mh.ProcessWatermarkBoard(2)
        shares = {h: [] for h in host_edges}
        errors = []

        def work(h):
            try:
                shares[h] = list(
                    mh.multihost_tumbling_windows(
                        _batches([e for e in host_edges[h]]),
                        window_ms,
                        h,
                        board,
                        timeout=30.0,
                    )
                )
            except BaseException as e:  # surfaced by the main thread
                errors.append(e)

        ts = [threading.Thread(target=work, args=(h,)) for h in host_edges]
        for t in ts:
            t.start()
        for t in ts:
            t.join(timeout=60.0)
        assert not errors, errors
        return mh.merge_pane_shares([iter(shares[0]), iter(shares[1])])

    runner = MeshAggregationRunner(ConnectedComponents())
    stream = EdgeStream.from_collection([], cfg)  # pane source overridden
    got = [
        str(r[0])
        for r in runner.run(stream, panes=gathered_panes)
    ]

    union = sorted(
        host_edges[0] + host_edges[1], key=lambda e: e[2]
    )
    single = EdgeStream.from_collection(
        [(s, d, 0.0, t) for (s, d, t) in union], cfg, batch_size=4, with_time=True
    )
    want = [str(r[0]) for r in ConnectedComponents().run(single)]
    assert got == want


def test_merge_pane_shares_mixed_empty_val_share():
    """A host with no data closes empty shares with val=None (no val_proto
    learned); merging with peers' val-carrying shares must not die on the
    None/pytree mix."""
    from gelly_streaming_tpu.core.windows import WindowPane

    full = WindowPane(
        window_id=0,
        max_timestamp=99,
        src=np.array([1, 2], np.int32),
        dst=np.array([2, 3], np.int32),
        val=np.array([0.5, 0.25]),
        time=np.array([5, 6], np.int64),
    )
    empty = WindowPane(
        window_id=0,
        max_timestamp=99,
        src=np.empty((0,), np.int32),
        dst=np.empty((0,), np.int32),
        val=None,
        time=None,
    )
    merged = list(mh.merge_pane_shares([iter([full]), iter([empty])]))
    assert len(merged) == 1
    np.testing.assert_array_equal(merged[0].src, [1, 2])
    np.testing.assert_array_equal(merged[0].val, [0.5, 0.25])
    # diverged sequences fail loudly
    with pytest.raises(ValueError):
        list(mh.merge_pane_shares([iter([full]), iter([])]))

"""Multi-tenant job runtime (ISSUE 5): concurrent queries over one device
pipeline.

The contract under test: N concurrent jobs emit BIT-IDENTICAL record
sequences to the same queries run serially (the scheduler multiplexes
dispatch opportunities, never results) across the wire, windowed, and
owner-sharded planes; pause/resume and crash-resume ride the per-job
positional checkpoints; admission control rejects loudly; same-shape jobs
share executables (0 recompiles); and one slow sink cannot stall the rest.

Every threaded test carries ``timeout_cap`` (tests/conftest.py): a wedged
scheduler or completion queue must FAIL the test, not hang tier-1.
"""

import dataclasses
import os
import signal
import subprocess
import sys
import textwrap
import threading

import numpy as np
import pytest

import jax.numpy as jnp

from gelly_streaming_tpu.core.aggregation import SummaryBulkAggregation
from gelly_streaming_tpu.core.config import RuntimeConfig, StreamConfig
from gelly_streaming_tpu.core.stream import EdgeStream
from gelly_streaming_tpu.library.connected_components import (
    ConnectedComponents,
)
from gelly_streaming_tpu.runtime import (
    AdmissionError,
    JobManager,
    JobState,
)
from gelly_streaming_tpu.utils import metrics

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

pytestmark = pytest.mark.timeout_cap(300)

CAP = 1 << 12
WIN = 1 << 10
N = 8 * WIN
# aligned batch -> the packed-wire fast path with running emission
CFG_WIRE = StreamConfig(
    vertex_capacity=CAP, batch_size=1 << 9, ingest_window_edges=WIN
)
# misaligned batch -> the windowed runtime's ingestion panes
CFG_WINDOWED = StreamConfig(
    vertex_capacity=CAP, batch_size=(1 << 9) + 96, ingest_window_edges=WIN
)


def _graph(seed: int, n: int = N, cap: int = CAP):
    rng = np.random.default_rng(seed)
    return (
        rng.integers(0, cap, n).astype(np.int32),
        rng.integers(0, cap, n).astype(np.int32),
    )


def _cc_serial(cfg, s, d, checkpoint_path=None):
    out = EdgeStream.from_arrays(s, d, cfg).aggregate(
        ConnectedComponents(), checkpoint_path=checkpoint_path
    )
    return [np.asarray(rec[0].parent) for rec in out]


def _materialize_cc(records):
    return [np.asarray(rec[0].parent) for rec in records]


class EdgeCount(SummaryBulkAggregation):
    """NON-idempotent fold: re-folding any pane after a resume overcounts,
    so the final value proves exactly-once state (the async-pipeline
    tests' oracle, reused for the runtime's checkpoints)."""

    order_free = True

    @property
    def cache_token(self):
        return type(self)

    def initial_state(self, cfg):
        return jnp.zeros((), jnp.int32)

    def update(self, state, src, dst, val, mask):
        return state + jnp.sum(mask.astype(jnp.int32))

    def combine(self, a, b):
        return a + b


# ---------------------------------------------------------------------------
# concurrent-vs-serial emission parity
# ---------------------------------------------------------------------------


def _assert_four_jobs_match_serial(cfg):
    datasets = [_graph(seed) for seed in range(4)]
    serial = [_cc_serial(cfg, s, d) for s, d in datasets]
    with JobManager() as jm:
        jobs = [
            jm.submit_aggregation(
                EdgeStream.from_arrays(s, d, cfg),
                ConnectedComponents(),
                name=f"cc-{i}",
            )
            for i, (s, d) in enumerate(datasets)
        ]
        outs = [_materialize_cc(job.results()) for job in jobs]
        states = [job.state for job in jobs]
    assert states == [JobState.DONE] * 4
    for i, (want, got) in enumerate(zip(serial, outs)):
        assert len(want) == len(got), (i, len(want), len(got))
        for w, (a, b) in enumerate(zip(want, got)):
            assert np.array_equal(a, b), f"job {i} window {w} diverged"


def test_four_jobs_wire_plane_match_serial():
    _assert_four_jobs_match_serial(CFG_WIRE)


def test_four_jobs_windowed_plane_match_serial():
    _assert_four_jobs_match_serial(CFG_WINDOWED)


def test_four_jobs_async_windowed_plane_match_serial():
    # each job runs its own async window pipeline (depth 2) under the one
    # scheduler: pack/transfer threads and completion queues per job, all
    # dispatching through the shared executables
    _assert_four_jobs_match_serial(
        dataclasses.replace(CFG_WINDOWED, async_windows=2)
    )


def test_four_jobs_sharded_plane_match_serial():
    # the owner-sharded mesh streaming plane (2 shards of the virtual CPU
    # mesh) — one emission per job at stream end
    cfg = StreamConfig(
        vertex_capacity=CAP, batch_size=1 << 9, num_shards=2
    )
    datasets = [_graph(seed, n=4 * (1 << 9)) for seed in range(4)]
    serial = [_cc_serial(cfg, s, d) for s, d in datasets]
    with JobManager() as jm:
        jobs = [
            jm.submit_aggregation(
                EdgeStream.from_arrays(s, d, cfg),
                ConnectedComponents(),
                name=f"mesh-{i}",
            )
            for i, (s, d) in enumerate(datasets)
        ]
        outs = [_materialize_cc(job.results()) for job in jobs]
    for i, (want, got) in enumerate(zip(serial, outs)):
        assert len(want) == len(got)
        for a, b in zip(want, got):
            assert np.array_equal(a, b), f"mesh job {i} diverged"


# ---------------------------------------------------------------------------
# lifecycle: pause / resume / cancel
# ---------------------------------------------------------------------------


def test_pause_resume_emission_parity():
    s, d = _graph(7)
    serial = _cc_serial(CFG_WIRE, s, d)
    with JobManager() as jm:
        job = jm.submit_aggregation(
            EdgeStream.from_arrays(s, d, CFG_WIRE),
            ConnectedComponents(),
            name="pausable",
        )
        it = job.results()
        got = [next(it), next(it)]
        assert job.pause() is True
        assert job.state == JobState.PAUSED
        # paused: the iterator is suspended in place; nothing else arrives
        job.resume()
        got.extend(it)
    assert len(got) == len(serial)
    for want, rec in zip(serial, got):
        assert np.array_equal(want, np.asarray(rec[0].parent))


def test_pause_checkpoints_then_cancel_resubmit_is_exact(tmp_path):
    """Cancel a checkpointed job mid-stream and resubmit from its
    checkpoint: delivered records overlap at the boundary only
    (at-least-once, never a gap) and the non-idempotent final count is
    exact (state exactly-once)."""
    s, d = _graph(11)
    ck = str(tmp_path / "ck")
    cfg = CFG_WINDOWED
    serial = [
        int(rec[0])
        for rec in EdgeStream.from_arrays(s, d, cfg).aggregate(EdgeCount())
    ]
    with JobManager() as jm:
        job = jm.submit_aggregation(
            EdgeStream.from_arrays(s, d, cfg),
            EdgeCount(),
            name="count",
            checkpoint_path=ck,
        )
        it = job.results()
        first = [int(next(it)[0]), int(next(it)[0])]
        job.cancel(wait=True)
        first.extend(int(rec[0]) for rec in it)  # the queued tail delivers
        assert job.state == JobState.CANCELLED

        job2 = jm.submit_aggregation(
            EdgeStream.from_arrays(s, d, cfg),
            EdgeCount(),
            name="count-resumed",
            checkpoint_path=ck,
        )
        second = [int(rec[0]) for rec in job2.results()]
    assert second, "resumed job emitted nothing"
    overlap = len(first) + len(second) - len(serial)
    assert overlap >= 0, "cancel+resume dropped emissions (a gap)"
    assert first[: len(first) - overlap] + second == serial
    assert second[-1] == serial[-1] == len(s)


def test_cancel_mid_flight_async_job(tmp_path):
    """Cancelling an async-windowed job mid-flight returns promptly and
    terminally — its in-flight windows drain through the completion queue
    (arena recycle) rather than wedging the scheduler."""
    s, d = _graph(13)
    cfg = dataclasses.replace(CFG_WINDOWED, async_windows=3)
    with JobManager() as jm:
        job = jm.submit_aggregation(
            EdgeStream.from_arrays(s, d, cfg),
            ConnectedComponents(),
            name="doomed",
        )
        it = job.results()
        next(it)
        assert job.cancel(wait=True, timeout=60)
        assert job.state == JobState.CANCELLED
        # a second job over the same pipeline still runs clean after the
        # cancel (no leaked arenas / wedged prefetcher threads)
        ok = jm.submit_aggregation(
            EdgeStream.from_arrays(s, d, cfg),
            ConnectedComponents(),
            name="after",
        )
        assert len(_materialize_cc(ok.results())) == N // WIN


def test_pause_resume_on_finished_job_is_refused_not_raced():
    """pause()/resume() race the scheduler by nature, so an un-pausable
    state returns False (check+transition atomic under the manager lock)
    instead of throwing at the caller."""
    s, d = _graph(17, n=WIN)
    with JobManager() as jm:
        job = jm.submit_aggregation(
            EdgeStream.from_arrays(s, d, CFG_WIRE),
            ConnectedComponents(),
            name="one",
        )
        job.collect()
        assert job.wait(30) and job.state == JobState.DONE
        assert job.pause() is False
        assert job.resume() is False
        assert job.state == JobState.DONE


def test_shared_checkpoint_path_is_refused(tmp_path):
    """Two ACTIVE jobs interleaving saves into one snapshot file would
    corrupt both resumes — admission rejects the collision; per_job_file
    is the shared-prefix escape hatch."""
    from gelly_streaming_tpu.utils.checkpoint import per_job_file

    s, d = _graph(67)
    ck = str(tmp_path / "shared")
    with JobManager() as jm:
        gate = threading.Event()

        def held_source():
            gate.wait(60)
            return iter(())

        jm.submit(held_source, name="holder", checkpoint_path=ck)
        with pytest.raises(AdmissionError, match="already in use"):
            jm.submit_aggregation(
                EdgeStream.from_arrays(s, d, CFG_WIRE),
                ConnectedComponents(),
                name="collider",
                checkpoint_path=ck,
            )
        # the derived per-job files do not collide
        a = per_job_file(ck, "job-a")
        b = per_job_file(ck, "job-b")
        assert a != b and a.startswith(ck) and b.startswith(ck)
        jm.submit_aggregation(
            EdgeStream.from_arrays(s, d, CFG_WIRE),
            ConnectedComponents(),
            name="keyed",
            checkpoint_path=a,
        ).collect()
        gate.set()


def test_terminal_jobs_are_evicted_beyond_retention():
    """A long-lived manager must not grow without bound: older terminal
    jobs (and their per-job metrics rows) are evicted at submit, while the
    module totals keep their contribution."""
    metrics.reset_job_stats()
    s, d = _graph(71, n=WIN)
    with JobManager(RuntimeConfig(keep_terminal_jobs=2)) as jm:
        for i in range(5):
            job = jm.submit_aggregation(
                EdgeStream.from_arrays(s, d, CFG_WIRE),
                ConnectedComponents(),
                name=f"gen-{i}",
            )
            job.collect()
            assert job.wait(30)
        status = jm.status()
        # at most keep_terminal_jobs finished jobs + the newest one linger
        assert len(status["jobs"]) <= 3
        assert "gen-0" not in status["jobs"]
        # evicted per-job rows are gone, totals keep every job's records
        assert "gen-0" not in metrics.all_job_stats()
        assert metrics.job_totals()["job_records"] == 5 * 1
        # a terminal job's source closure was dropped at release time
        assert all(j._build is None for j in jm._jobs.values())


# ---------------------------------------------------------------------------
# the GeneratorExit drain (cancel recycles arenas through the drain path)
# ---------------------------------------------------------------------------


def test_async_merge_loop_close_drains_and_releases():
    """Closing the async Merger mid-stream (the cancel path) must run every
    dispatched-but-undrained window through the NORMAL drain — releasing
    its arenas exactly once — before GeneratorExit propagates."""
    from gelly_streaming_tpu.core import async_exec
    from gelly_streaming_tpu.core.windows import WindowPane

    agg = EdgeCount()
    cfg = StreamConfig(vertex_capacity=64, batch_size=32)
    released = []

    def panes():
        for w in range(8):
            pane = WindowPane(
                window_id=w,
                max_timestamp=-1,
                src=np.zeros((4,), np.int32),
                dst=np.zeros((4,), np.int32),
                val=None,
                time=None,
            )
            yield pane, w

    def fold(payload):
        return jnp.zeros((), jnp.int32) + payload

    gen = async_exec.async_merge_loop(
        agg,
        cfg,
        panes(),
        fold,
        checkpoint_path=None,
        restore=False,
        unwrap=True,
        depth=4,
        release=released.append,
    )
    next(gen)
    next(gen)
    gen.close()
    # windows 0..5 dispatched (2 drained by the yields, 4 in flight at
    # close); every one released exactly once, through the drain path
    assert released == [0, 1, 2, 3, 4, 5]


# ---------------------------------------------------------------------------
# admission control
# ---------------------------------------------------------------------------


def test_admission_job_cap_rejects():
    s, d = _graph(19, n=WIN)
    with JobManager(RuntimeConfig(max_jobs=2)) as jm:
        gate = threading.Event()

        def held_source():
            gate.wait(60)  # holds its job slot open until released
            return iter(())

        held = [
            jm.submit(held_source, name=f"hold-{i}") for i in range(2)
        ]
        with pytest.raises(AdmissionError, match="job cap"):
            jm.submit_aggregation(
                EdgeStream.from_arrays(s, d, CFG_WIRE),
                ConnectedComponents(),
            )
        gate.set()
        for job in held:
            job.collect()


def test_admission_byte_cap_rejects_and_releases():
    s, d = _graph(23, n=WIN)
    one_job = ConnectedComponents().state_nbytes(CFG_WIRE)
    assert one_job > 0
    with JobManager(
        RuntimeConfig(max_state_bytes=int(one_job * 1.5))
    ) as jm:
        first = jm.submit_aggregation(
            EdgeStream.from_arrays(s, d, CFG_WIRE),
            ConnectedComponents(),
            name="fits",
        )
        with pytest.raises(AdmissionError, match="state-byte cap"):
            jm.submit_aggregation(
                EdgeStream.from_arrays(s, d, CFG_WIRE),
                ConnectedComponents(),
                name="rejected",
            )
        first.collect()
        assert first.wait(30)
        # terminal jobs return their budget: the next submit is admitted
        again = jm.submit_aggregation(
            EdgeStream.from_arrays(s, d, CFG_WIRE),
            ConnectedComponents(),
            name="admitted-after-release",
        )
        again.collect()


def test_rescale_budget_swap_is_atomic_under_the_admission_lock():
    """The elastic control plane's re-pricing (ISSUE 11): a draining
    job's state bytes move into a swap reservation UNDER the admission
    lock, so across the whole drain -> resubmit window (a) a same-size
    swap never transiently double-books against the cap, and (b) a
    concurrent tenant can never steal the freed budget mid-swap."""
    s, d = _graph(29, n=WIN)
    one_job = ConnectedComponents().state_nbytes(CFG_WIRE)
    with JobManager(RuntimeConfig(max_state_bytes=one_job)) as jm:
        gate = threading.Event()

        def held_source():
            gate.wait(60)
            return iter(())

        job = jm.submit(held_source, name="scaling", state_bytes=one_job)
        # (a) cap == one job's bytes: a same-size re-pricing must fit —
        # if old and new were ever both charged, this would reject
        reserved = jm.begin_rescale(job, one_job)
        assert reserved == one_job
        assert job.state_bytes == 0  # budget moved, not freed
        # (b) the reservation is committed budget: a concurrent tenant
        # cannot grab it while the swap is in flight
        with pytest.raises(AdmissionError, match="reserved"):
            jm.submit_aggregation(
                EdgeStream.from_arrays(s, d, CFG_WIRE),
                ConnectedComponents(),
                name="thief",
            )
        gate.set()
        jm.cancel(job, wait=True)
        status = jm.status()
        # the drained job's release returned NOTHING to the pool (its
        # budget lives in the reservation): admitted 0, reserved one_job
        assert status["admitted_state_bytes"] == 0
        assert status["reserved_state_bytes"] == one_job
        # the resubmit consumes the reservation exactly
        resubmitted = jm.submit(
            lambda: iter(()),
            name="scaling",
            state_bytes=one_job,
            reserved_bytes=reserved,
        )
        status = jm.status()
        assert status["admitted_state_bytes"] == one_job
        assert status["reserved_state_bytes"] == 0
        # once the rescaled job finishes, the budget is free again
        resubmitted.collect()
        assert resubmitted.wait(30)
        after = jm.submit_aggregation(
            EdgeStream.from_arrays(s, d, CFG_WIRE),
            ConnectedComponents(),
            name="after",
        )
        after.collect()


def test_rescale_budget_abort_returns_reservation():
    """A swap that dies mid-flight must return its reservation to the
    open pool — never leak budget out of circulation."""
    one_job = ConnectedComponents().state_nbytes(CFG_WIRE)
    s, d = _graph(31, n=WIN)
    with JobManager(RuntimeConfig(max_state_bytes=one_job)) as jm:
        job = jm.submit(lambda: iter(()), name="dies", state_bytes=one_job)
        reserved = jm.begin_rescale(job, one_job)
        jm.cancel(job, wait=True)
        jm.abort_rescale(reserved)
        assert jm.status()["reserved_state_bytes"] == 0
        ok = jm.submit_aggregation(
            EdgeStream.from_arrays(s, d, CFG_WIRE),
            ConnectedComponents(),
            name="pool-restored",
        )
        ok.collect()


def test_rescale_reserves_the_job_slot_against_concurrent_submits():
    """The swap holds its max_jobs SLOT too: mid-drain the old job reads
    terminal, and without the slot reservation a concurrent submit could
    fill the cap and strand the resubmit (refused 'job cap'), killing
    the rescaled job."""
    s, d = _graph(37, n=WIN)
    with JobManager(RuntimeConfig(max_jobs=1)) as jm:
        job = jm.submit(lambda: iter(()), name="scaling", state_bytes=0)
        reserved = jm.begin_rescale(job, 0)
        jm.cancel(job, wait=True)  # 0 active jobs — but 1 rescaling
        with pytest.raises(AdmissionError, match="rescaling"):
            jm.submit_aggregation(
                EdgeStream.from_arrays(s, d, CFG_WIRE),
                ConnectedComponents(),
                name="slot-thief",
            )
        # the swap's own resubmit consumes exactly the reserved slot
        resub = jm.submit(
            lambda: iter(()),
            name="scaling",
            state_bytes=0,
            reserved_bytes=reserved,
        )
        resub.collect()
        jm.wait_all(30)
        ok = jm.submit_aggregation(
            EdgeStream.from_arrays(s, d, CFG_WIRE),
            ConnectedComponents(),
            name="after-slot",
        )
        ok.collect()


def test_abort_rescale_restores_a_live_jobs_budget():
    """The drain-failed path: a job whose cancel never completed is still
    RUNNING — aborting the swap must re-charge its bytes (a live summary
    with state_bytes=0 would let admission stack a second full job on
    top) and release both reservations."""
    one_job = ConnectedComponents().state_nbytes(CFG_WIRE)
    with JobManager(RuntimeConfig(max_state_bytes=one_job)) as jm:
        gate = threading.Event()

        def held_source():
            gate.wait(60)
            return iter(())

        job = jm.submit(held_source, name="undrainable", state_bytes=one_job)
        reserved = jm.begin_rescale(job, one_job)
        assert job.state_bytes == 0
        # the drain "times out": the job is still live; abort restores
        jm.abort_rescale(reserved, job=job, restore_state_bytes=one_job)
        assert job.state_bytes == one_job
        status = jm.status()
        assert status["admitted_state_bytes"] == one_job
        assert status["reserved_state_bytes"] == 0
        # the cap is exactly honest again: a second job is refused...
        with pytest.raises(AdmissionError, match="state-byte cap"):
            jm.submit(lambda: iter(()), name="over", state_bytes=one_job)
        gate.set()
        jm.cancel(job, wait=True)
        # ...and a TERMINAL job's abort restores nothing (budget is free)
        jm.begin_rescale(job, one_job)  # held is already 0
        jm.abort_rescale(reserved, job=job, restore_state_bytes=one_job)
        assert jm.status()["admitted_state_bytes"] == 0


def test_rescale_growth_beyond_cap_rejects_and_leaves_job_intact():
    """Re-pricing at a BIGGER geometry admission-checks the growth; a
    rejection leaves the job exactly as it was (still admitted)."""
    one_job = ConnectedComponents().state_nbytes(CFG_WIRE)
    with JobManager(RuntimeConfig(max_state_bytes=one_job)) as jm:
        gate = threading.Event()

        def held_source():
            gate.wait(60)
            return iter(())

        job = jm.submit(held_source, name="fixed", state_bytes=one_job)
        with pytest.raises(AdmissionError, match="re-pricing"):
            jm.begin_rescale(job, 2 * one_job)
        assert job.state_bytes == one_job  # untouched
        assert jm.status()["reserved_state_bytes"] == 0
        gate.set()
        jm.cancel(job, wait=True)


# ---------------------------------------------------------------------------
# executable sharing across jobs (the co-scheduling thesis)
# ---------------------------------------------------------------------------


def test_zero_recompiles_across_same_shape_jobs():
    from gelly_streaming_tpu.core import compile_cache

    warm_s, warm_d = _graph(29)
    _cc_serial(CFG_WIRE, warm_s, warm_d)  # first job's warmup compiles
    compile_cache.reset_stats()
    datasets = [_graph(seed) for seed in (31, 37, 41)]
    with JobManager() as jm:
        jobs = [
            jm.submit_aggregation(
                EdgeStream.from_arrays(s, d, CFG_WIRE),
                ConnectedComponents(),
                name=f"warmed-{i}",
            )
            for i, (s, d) in enumerate(datasets)
        ]
        for job in jobs:
            job.collect()
    stats = compile_cache.stats()
    assert stats["recompiles"] == 0, stats
    assert stats["compiles"] == 0, (
        "same-shape jobs should reuse the warm executables outright",
        stats,
    )


# ---------------------------------------------------------------------------
# isolation: one slow sink cannot stall other jobs
# ---------------------------------------------------------------------------


def test_slow_sink_does_not_stall_other_jobs():
    s, d = _graph(43)
    gate = threading.Event()
    slow_records = []

    def slow_sink(rec):
        gate.wait(120)  # wedged until the fast job proves it finished
        slow_records.append(rec)

    with JobManager(RuntimeConfig(job_queue_depth=2)) as jm:
        slow = jm.submit_aggregation(
            EdgeStream.from_arrays(s, d, CFG_WIRE),
            ConnectedComponents(),
            name="slow",
            sink=slow_sink,
        )
        fast = jm.submit_aggregation(
            EdgeStream.from_arrays(*_graph(47), CFG_WIRE),
            ConnectedComponents(),
            name="fast",
        )
        out = _materialize_cc(fast.results())
        assert len(out) == N // WIN
        assert fast.state == JobState.DONE
        assert not slow.wait(0), "slow job should still be in flight"
        status = jm.status()
        assert status["jobs"]["slow"]["job_queue_full_skips"] >= 1
        gate.set()
        assert slow.wait(60)
        assert slow.state == JobState.DONE
    assert len(slow_records) == N // WIN


def test_one_job_failure_is_isolated():
    def boom():
        yield (1,)
        raise ValueError("query exploded")

    s, d = _graph(53)
    with JobManager() as jm:
        bad = jm.submit(boom, name="bad")
        good = jm.submit_aggregation(
            EdgeStream.from_arrays(s, d, CFG_WIRE),
            ConnectedComponents(),
            name="good",
        )
        out = _materialize_cc(good.results())
        assert len(out) == N // WIN
        assert bad.wait(30)
        assert bad.state == JobState.FAILED
        assert isinstance(bad.error, ValueError)
        from gelly_streaming_tpu.runtime import JobError

        with pytest.raises(JobError, match="query exploded"):
            bad.collect()


# ---------------------------------------------------------------------------
# status / metrics scoping
# ---------------------------------------------------------------------------


def test_status_reports_per_job_counters_and_totals():
    metrics.reset_job_stats()
    datasets = [_graph(seed) for seed in (59, 61)]
    with JobManager() as jm:
        jobs = [
            jm.submit_aggregation(
                EdgeStream.from_arrays(s, d, CFG_WIRE),
                ConnectedComponents(),
                name=f"meter-{i}",
            )
            for i, (s, d) in enumerate(datasets)
        ]
        for job in jobs:
            job.collect()
        status = jm.status()
    windows = N // WIN
    for i in range(2):
        row = status["jobs"][f"meter-{i}"]
        assert row["state"] == JobState.DONE
        assert row["job_records"] == windows
        assert row["job_dispatches"] == windows
        assert row["job_edges"] == N
        assert row["edges_hint"] == N  # the source's total-edge hint
        assert row["job_dispatch_s"] > 0
    # module aggregates preserved as sums over the per-job rows
    per_job = metrics.all_job_stats()
    totals = metrics.job_totals()
    for key in ("job_records", "job_dispatches", "job_edges"):
        assert totals[key] == sum(row[key] for row in per_job.values())


# ---------------------------------------------------------------------------
# SIGKILL-mid-stream: two jobs resume from their independent checkpoints
# ---------------------------------------------------------------------------

_CHILD = textwrap.dedent(
    """
    import os, signal, sys
    os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=1"
    sys.path.insert(0, {repo!r})
    import jax
    jax.config.update("jax_platforms", "cpu")
    import numpy as np
    import jax.numpy as jnp
    from gelly_streaming_tpu.core.aggregation import SummaryBulkAggregation
    from gelly_streaming_tpu.core.config import StreamConfig
    from gelly_streaming_tpu.core.stream import EdgeStream
    from gelly_streaming_tpu.runtime import JobManager

    class EdgeCount(SummaryBulkAggregation):
        order_free = True
        @property
        def cache_token(self):
            return type(self)
        def initial_state(self, cfg):
            return jnp.zeros((), jnp.int32)
        def update(self, state, src, dst, val, mask):
            return state + jnp.sum(mask.astype(jnp.int32))
        def combine(self, a, b):
            return a + b

    kill_after = int(os.environ.get("KILL_AFTER_SAVES", "0"))
    if kill_after:
        import gelly_streaming_tpu.utils.checkpoint as ckpt
        real = ckpt.save_state
        n = [0]
        def hooked(p, s):
            real(p, s)
            n[0] += 1
            if n[0] >= kill_after:
                os.kill(os.getpid(), signal.SIGKILL)  # no cleanup, no atexit
        ckpt.save_state = hooked

    cfg = StreamConfig(
        vertex_capacity=64,
        batch_size=96,
        # 128 % 96 != 0 -> the WINDOWED runtime (not the wire fast path)
        ingest_window_edges=128,
    )
    finals = {{}}
    with JobManager() as jm:
        jobs = []
        for name, seed, ck in (("a", 5, {ck_a!r}), ("b", 6, {ck_b!r})):
            rng = np.random.default_rng(seed)
            src = rng.integers(0, 64, 1024).astype(np.int32)
            dst = rng.integers(0, 64, 1024).astype(np.int32)
            stream = EdgeStream.from_arrays(src, dst, cfg)
            jobs.append(
                (name, jm.submit_aggregation(
                    stream, EdgeCount(), name=name, checkpoint_path=ck
                ))
            )
        for name, job in jobs:
            out = job.collect()
            finals[name] = int(out[-1][0])
    print("FINAL", finals["a"], finals["b"])
    """
)


def _run_child(script, env_extra):
    env = dict(os.environ, **env_extra)
    return subprocess.run(
        [sys.executable, str(script)],
        env=env,
        capture_output=True,
        timeout=300,
    )


def test_sigkill_two_jobs_resume_from_independent_checkpoints(tmp_path):
    """SIGKILL the manager mid-stream with two checkpointed jobs in flight;
    a fresh process resubmits both against their own checkpoints and each
    completes its non-idempotent count exactly — positions never merge."""
    ck_a = str(tmp_path / "ck_a")
    ck_b = str(tmp_path / "ck_b")
    script = tmp_path / "child.py"
    script.write_text(_CHILD.format(repo=REPO, ck_a=ck_a, ck_b=ck_b))

    first = _run_child(script, {"KILL_AFTER_SAVES": "6"})
    assert first.returncode == -signal.SIGKILL, (
        first.returncode,
        first.stdout,
        first.stderr,
    )
    # both jobs made independent progress before the kill
    assert os.path.exists(ck_a + ".npz") or os.path.exists(ck_b + ".npz")

    second = _run_child(script, {})
    assert second.returncode == 0, second.stderr.decode()
    assert b"FINAL 1024 1024" in second.stdout, (
        second.stdout,
        second.stderr,
    )


# ---------------------------------------------------------------------------
# gelly-serve
# ---------------------------------------------------------------------------


def test_serve_main_runs_jobs_to_done(capsys):
    from gelly_streaming_tpu.runtime import serve

    rc = serve.main(
        [
            "--jobs",
            "2",
            "--query",
            "cc",
            "--edges",
            "8192",
            "--capacity",
            "4096",
            "--window-edges",
            "4096",
            "--status-interval",
            "0",
        ]
    )
    assert rc == 0
    out = capsys.readouterr()
    assert "2 job(s)" in out.out
    assert "DONE" in out.err

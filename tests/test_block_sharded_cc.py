"""Block-distributed CC labels over the 8-device mesh (VERDICT r2 missing #4).

The replicated fixpoint holds parent[C] on EVERY device; these tests pin the
O(C/S)-per-shard design: ring-lookup remote labels, relax + pointer-halving
rounds, streaming merges across panes, and exact agreement with a host
union-find's min-root labels.
"""

import numpy as np
import pytest

from gelly_streaming_tpu.core.config import StreamConfig
from gelly_streaming_tpu.core.stream import EdgeStream
from gelly_streaming_tpu.library.connected_components import (
    BlockShardedCC,
    init_label_blocks,
    unshard_labels,
)


def _host_min_labels(capacity, edges):
    from fixtures import host_min_labels

    return host_min_labels(
        capacity, [e[0] for e in edges], [e[1] for e in edges]
    )


def _run(edges, capacity, batch_size=64):
    cfg = StreamConfig(vertex_capacity=capacity, batch_size=batch_size)
    stream = EdgeStream.from_collection(edges, cfg, batch_size=batch_size)
    cc = BlockShardedCC()
    out = list(cc.run(stream))
    return unshard_labels(out[-1][0]), cc


def test_matches_host_union_find_random():
    rng = np.random.default_rng(0)
    c = 1024
    edges = list(
        zip(
            rng.integers(0, c, 600).tolist(),
            rng.integers(0, c, 600).tolist(),
        )
    )
    labels, cc = _run(edges, c)
    np.testing.assert_array_equal(labels, _host_min_labels(c, edges))


def test_state_is_block_distributed():
    labels, cc = _run([(0, 1)], 1024)
    # per-shard label state is C/S rows, not C
    s = cc.num_shards
    assert init_label_blocks(1024, s).shape == (s, 1024 // s)


def test_streaming_lazy_merge_across_panes():
    # pane 1 merges {5, 9}; pane 2's edge (1, 5) must drag 9 down to 1 even
    # though no pane-2 edge touches 9 — the halving pass compresses through
    # the persistent label table
    c = 16
    cfg = StreamConfig(vertex_capacity=c, batch_size=1)
    stream = EdgeStream.from_collection([(5, 9), (1, 5)], cfg, batch_size=1)
    cc = BlockShardedCC()
    outs = list(cc.run(stream))
    final = unshard_labels(outs[-1][0])
    assert final[9] == final[5] == final[1] == 1


def test_path_graph_worst_diameter():
    c = 64
    edges = [(i, i + 1) for i in range(c - 1)]
    labels, _ = _run(edges, c, batch_size=16)
    assert (labels == 0).all()


def test_unshard_roundtrip():
    blocks = init_label_blocks(32, 8)
    np.testing.assert_array_equal(unshard_labels(blocks), np.arange(32))


def test_block_sharded_cc_accepts_pane_override():
    from gelly_streaming_tpu.core.windows import WindowPane

    c = 64
    cfg = StreamConfig(vertex_capacity=c, batch_size=4)

    def panes():
        yield WindowPane(
            window_id=0,
            max_timestamp=99,
            src=np.array([1, 2], np.int32),
            dst=np.array([2, 3], np.int32),
            val=None,
            time=None,
        )

    cc = BlockShardedCC()
    stream = EdgeStream.from_collection([], cfg)
    outs = list(cc.run(stream, panes=panes))
    labels = unshard_labels(outs[-1][0])
    assert labels[1] == labels[2] == labels[3] == 1


def test_skewed_hub_graph_no_capacity_blowup():
    """A hub owning ~all edges: the unrouted design splits edges evenly over
    shards regardless of key ownership (labels travel to the edges via ring
    passes), so skew cannot blow up any shard's bucket."""
    c = 256
    hub = 7
    edges = [(hub, i) for i in range(c) if i != hub]
    labels, cc = _run(edges, c, batch_size=64)
    expect = _host_min_labels(c, edges)
    np.testing.assert_array_equal(labels, expect)
    # per-shard bucket stays ~E/S even though one vertex owns every edge
    s, d, m = cc._split_pane(
        np.array([e[0] for e in edges], np.int32),
        np.array([e[1] for e in edges], np.int32),
    )
    assert s.shape[1] <= 2 * (len(edges) // cc.num_shards + 1)


def test_block_sharded_cc_kill_and_resume(tmp_path):
    """Positional checkpoints on the block-distributed runner: a killed run
    resumes from the last snapshot pane without refolding it."""
    import os

    ckpt = os.path.join(str(tmp_path), "bcc.npz")
    c = 64
    cfg = StreamConfig(vertex_capacity=c, batch_size=2, window_ms=100)
    edges = [
        (1, 2, 0.0, 10),
        (3, 4, 0.0, 110),
        (2, 3, 0.0, 210),
        (5, 6, 0.0, 310),
    ]

    def stream():
        return EdgeStream.from_collection(edges, cfg, batch_size=2, with_time=True)

    full = [
        unshard_labels(r[0]) for r in BlockShardedCC().run(stream())
    ]

    # crash after two panes (generator abandoned mid-stream)
    it = iter(BlockShardedCC().run(stream(), checkpoint_path=ckpt))
    first_two = [next(it), next(it)]
    it.close()
    assert os.path.exists(ckpt)

    resumed = [
        unshard_labels(r[0])
        for r in BlockShardedCC().run(stream(), checkpoint_path=ckpt)
    ]
    # panes snapshot before the crash are skipped; the tail re-emits and the
    # final labels match the uninterrupted run exactly
    assert len(resumed) < len(full)
    np.testing.assert_array_equal(resumed[-1], full[-1])
    np.testing.assert_array_equal(unshard_labels(first_two[1][0]), full[1])


def test_block_sharded_cc_under_supervisor(tmp_path):
    """run_supervised + positional checkpoints on the block-distributed
    runner: a source that crashes once mid-stream recovers and the final
    labels match an uninterrupted run."""
    import os

    from gelly_streaming_tpu.utils.recovery import run_supervised

    ckpt = os.path.join(str(tmp_path), "sup.npz")
    c = 64
    cfg = StreamConfig(vertex_capacity=c, batch_size=2, window_ms=100)
    edges = [
        (1, 2, 0.0, 10),
        (3, 4, 0.0, 110),
        (2, 3, 0.0, 210),
        (5, 6, 0.0, 310),
    ]
    crashes = {"left": 1}

    def flaky_batches():
        stream = EdgeStream.from_collection(edges, cfg, batch_size=2, with_time=True)
        for i, b in enumerate(stream.batches()):
            if i == 1 and crashes["left"]:
                crashes["left"] -= 1
                raise IOError("source hiccup")
            yield b

    class _Src:
        """Minimal stream shim: cfg + replayable batches."""

        def __init__(self):
            self.cfg = cfg

        def batches(self):
            return flaky_batches()

    def make_stream():
        return BlockShardedCC().run(_Src(), checkpoint_path=ckpt)

    got = list(run_supervised(make_stream, max_restarts=2))
    clean = list(
        BlockShardedCC().run(
            EdgeStream.from_collection(edges, cfg, batch_size=2, with_time=True)
        )
    )
    np.testing.assert_array_equal(
        unshard_labels(got[-1][0]), unshard_labels(clean[-1][0])
    )


def test_block_sharded_cc_multi_pane_cross_pane_merges():
    """Regression (round 4): hooking must write the smaller ROOT into the
    larger root's row, never new minima into endpoint rows — endpoint
    writes sever the pointer that witnesses an earlier pane's merge, so a
    later pane connecting two old components left part of one component on
    a stale label.  Random multi-pane streams over several seeds must match
    a host union-find exactly."""
    import numpy as np

    from gelly_streaming_tpu.core.config import StreamConfig
    from gelly_streaming_tpu.core.stream import EdgeStream
    from gelly_streaming_tpu.core.types import EdgeBatch
    from gelly_streaming_tpu.library.connected_components import (
        BlockShardedCC,
        unshard_labels,
    )

    C = 1 << 10
    for seed in (11, 23, 47):
        rng = np.random.default_rng(seed)
        src = rng.integers(0, C, 256).astype(np.int32)
        dst = rng.integers(0, C, 256).astype(np.int32)
        cfg = StreamConfig(
            vertex_capacity=C, batch_size=64, ingest_window_edges=80
        )

        def batches():
            for i in range(0, 256, 64):
                yield EdgeBatch.from_arrays(src[i : i + 64], dst[i : i + 64])

        outs = list(BlockShardedCC().run(EdgeStream.from_batches(batches, cfg)))
        assert len(outs) == 4  # 256 edges at 80/pane
        labels = unshard_labels(outs[-1][0])

        parent = np.arange(C)

        def find(v):
            while parent[v] != v:
                parent[v] = parent[parent[v]]
                v = parent[v]
            return v

        for a, b in zip(src, dst):
            ra, rb = find(int(a)), find(int(b))
            if ra != rb:
                parent[max(ra, rb)] = min(ra, rb)
        expect = np.array([find(v) for v in range(C)])
        assert np.array_equal(labels, expect), f"seed {seed}"

"""Shared test fixtures: the reference's sample graph and golden helpers.

The 7-edge / 5-vertex fixture mirrors GraphStreamTestUtils.getLongLongEdges
(test/GraphStreamTestUtils.java:55-68); golden comparisons are order-insensitive
like Flink's compareResultsByLinesInMemory.
"""

from gelly_streaming_tpu.core.config import StreamConfig
from gelly_streaming_tpu.core.stream import EdgeStream

LONG_LONG_EDGES = [
    (1, 2, 12),
    (1, 3, 13),
    (2, 3, 23),
    (3, 4, 34),
    (3, 5, 35),
    (4, 5, 45),
    (5, 1, 51),
]

CFG = StreamConfig(vertex_capacity=16, max_degree=16, batch_size=4)


def long_long_stream(batch_size=None, cfg=CFG):
    return EdgeStream.from_collection(
        LONG_LONG_EDGES, cfg, batch_size=batch_size
    )


def assert_lines(output_lines, expected: str):
    """Order-insensitive golden compare (compareResultsByLinesInMemory analog)."""
    got = sorted(output_lines)
    want = sorted(l for l in expected.strip().split("\n") if l)
    assert got == want, f"\n got: {got}\nwant: {want}"


def host_min_labels(capacity, src, dst):
    """Reference union-find (min-root labels) for cross-checking CC kernels."""
    import numpy as np

    parent = np.arange(capacity)

    def find(v):
        while parent[v] != v:
            parent[v] = parent[parent[v]]
            v = parent[v]
        return v

    for a, b in zip(src, dst):
        ra, rb = find(int(a)), find(int(b))
        if ra != rb:
            parent[max(ra, rb)] = min(ra, rb)
    return np.array([find(v) for v in range(capacity)])

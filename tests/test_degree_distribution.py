"""Fully-dynamic degree distribution tests.

Goldens from util/ExamplesTestData.java DEGREES_DATA/RESULT (:36-46) and the
degree-zero case DEGREES_DATA_ZERO/RESULT_ZERO (:48-67), exercised through
DegreeDistributionITCase semantics."""

import jax.numpy as jnp

from gelly_streaming_tpu.core.config import StreamConfig
from gelly_streaming_tpu.core.stream import EdgeStream
from gelly_streaming_tpu.core.types import EdgeBatch
from gelly_streaming_tpu.library.degree_distribution import DegreeDistribution

CFG = StreamConfig(vertex_capacity=16, max_degree=16)

DEGREES_DATA = [
    (1, 2, +1), (2, 3, +1), (1, 4, +1), (2, 3, -1), (3, 4, +1), (1, 2, -1),
]
DEGREES_RESULT = [
    (1, 1), (1, 2),
    (2, 1), (1, 1), (1, 2),
    (2, 2), (1, 1), (1, 2),
    (1, 3), (2, 1), (1, 2),
    (1, 3), (2, 2), (1, 2),
    (1, 3), (2, 1), (1, 2),
]

DEGREES_DATA_ZERO = DEGREES_DATA + [(2, 3, -1)]
DEGREES_RESULT_ZERO = DEGREES_RESULT + [(1, 1)]


def _signed_stream(events, batch_size=None):
    bs = batch_size or len(events)

    def factory():
        for i in range(0, len(events), bs):
            chunk = events[i : i + bs]
            yield EdgeBatch.from_arrays(
                [e[0] for e in chunk],
                [e[1] for e in chunk],
                sign=[e[2] for e in chunk],
                pad_to=bs,
            )

    return EdgeStream.from_batches(factory, CFG)


def test_degree_distribution_golden():
    recs = DegreeDistribution().run(_signed_stream(DEGREES_DATA)).collect()
    assert recs == DEGREES_RESULT


def test_degree_distribution_zero_golden():
    recs = DegreeDistribution().run(_signed_stream(DEGREES_DATA_ZERO)).collect()
    assert recs == DEGREES_RESULT_ZERO


def test_degree_distribution_batch_invariant():
    for bs in (1, 2, 7):
        recs = (
            DegreeDistribution()
            .run(_signed_stream(DEGREES_DATA_ZERO, batch_size=bs))
            .collect()
        )
        assert recs == DEGREES_RESULT_ZERO

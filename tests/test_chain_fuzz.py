"""Differential fuzz: random transformation chains, wire path vs simulated.

The packed-wire fast path runs the SAME stage pipeline as the simulated
runtime but inside one fused jitted step after a device-side unpack
(core/aggregation.py).  A divergence between the two executions of an
identical chain is a fast-path bug by definition — this sweep composes
random chains of map/filter/reverse/undirected/distinct over seeded random
edge streams and asserts both paths produce identical CC labels and edge
counts.  (from_collection never exposes wire arrays, so it always takes the
simulated path; from_arrays rides the wire.)
"""

import numpy as np
import pytest

import jax

from gelly_streaming_tpu.core.config import StreamConfig
from gelly_streaming_tpu.core.stream import EdgeStream
from gelly_streaming_tpu.library.connected_components import ConnectedComponents
from gelly_streaming_tpu.ops import unionfind as uf


CAP = 64

# (name, stream -> stream); predicates/maps are jax-traceable and pure
CHAIN_OPS = [
    ("rev", lambda s: s.reverse()),
    ("und", lambda s: s.undirected()),
    ("dis", lambda s: s.distinct()),
    ("fe_mod", lambda s: s.filter_edges(lambda a, b, v: (a + b) % 3 != 0)),
    ("fv_half", lambda s: s.filter_vertices(lambda v: v < CAP // 2)),
    ("fe_ne", lambda s: s.filter_edges(lambda a, b, v: a != b)),
    # map sets batch.val on both paths (the wire unpack constructs val=None;
    # a fused-step divergence in valued batches would surface here)
    ("map_sum", lambda s: s.map_edges(lambda a, b, v: a + b)),
]


_compress_j = jax.jit(uf.compress)


def _labels(out):
    return np.asarray(_compress_j(out[-1][0].parent))


@pytest.mark.parametrize("seed", range(8))
def test_random_chain_wire_matches_simulated(seed):
    rng = np.random.default_rng(seed)
    n = int(rng.integers(50, 400))
    src = rng.integers(0, CAP, n).astype(np.int32)
    dst = rng.integers(0, CAP, n).astype(np.int32)
    batch = int(rng.choice([16, 32, 64]))
    ops = [CHAIN_OPS[i] for i in rng.choice(len(CHAIN_OPS), rng.integers(0, 4))]

    cfg = StreamConfig(vertex_capacity=CAP, batch_size=batch)
    wire_stream = EdgeStream.from_arrays(src, dst, cfg)
    sim_stream = EdgeStream.from_collection(
        list(zip(src.tolist(), dst.tolist())), cfg, batch_size=batch
    )
    for _, op in ops:
        wire_stream = op(wire_stream)
        sim_stream = op(sim_stream)

    agg = ConnectedComponents()
    assert agg._wire_eligible(wire_stream)
    assert not agg._wire_eligible(sim_stream)
    wire_out = wire_stream.aggregate(ConnectedComponents()).collect()
    sim_out = sim_stream.aggregate(ConnectedComponents()).collect()
    names = [name for name, _ in ops]
    np.testing.assert_array_equal(
        _labels(wire_out), _labels(sim_out), err_msg=f"chain={names}"
    )
    # seen-vertex sets must also agree (CC labels alone can mask drops)
    np.testing.assert_array_equal(
        np.asarray(wire_out[-1][0].seen),
        np.asarray(sim_out[-1][0].seen),
        err_msg=f"chain={names}",
    )


@pytest.mark.parametrize("seed", range(4))
def test_random_chain_with_ingestion_panes_matches_global(seed):
    """The same random chains under ingestion-time panes: the FINAL running
    summary must equal the single-global-pane result on both an aligned pane
    size (stays on the wire fast path) and a misaligned one (pane assembler
    path) — panes must never drop, duplicate, or reorder chain output."""
    rng = np.random.default_rng(100 + seed)
    n = int(rng.integers(50, 400))
    src = rng.integers(0, CAP, n).astype(np.int32)
    dst = rng.integers(0, CAP, n).astype(np.int32)
    batch = int(rng.choice([16, 32, 64]))
    ops = [CHAIN_OPS[i] for i in rng.choice(len(CHAIN_OPS), rng.integers(0, 4))]
    names = [name for name, _ in ops]

    def run(ingest_edges, expect_wire=None):
        cfg = StreamConfig(
            vertex_capacity=CAP,
            batch_size=batch,
            ingest_window_edges=ingest_edges,
        )
        stream = EdgeStream.from_arrays(src, dst, cfg)
        for _, op in ops:
            stream = op(stream)
        agg = ConnectedComponents()
        if expect_wire is not None:  # pin which execution path runs
            assert agg._wire_eligible(stream) == expect_wire
        return stream.aggregate(agg).collect()

    plain = run(0)
    aligned = run(batch, expect_wire=True)  # one pane/batch: wire fast path
    misaligned = run(max(1, batch - 3), expect_wire=False)  # assembler path
    for variant, out in (("aligned", aligned), ("misaligned", misaligned)):
        np.testing.assert_array_equal(
            _labels(out),
            _labels(plain),
            err_msg=f"chain={names} panes={variant}",
        )
        np.testing.assert_array_equal(
            np.asarray(out[-1][0].seen),
            np.asarray(plain[-1][0].seen),
            err_msg=f"chain={names} panes={variant}",
        )


@pytest.mark.parametrize("seed", range(6))
def test_random_chain_sliding_reduce_matches_host(seed):
    """Random transform chain -> sliding slice -> reduce, differentially
    against a host model applying the same chain then windowing by hand."""
    from gelly_streaming_tpu.core.types import EdgeDirection

    rng = np.random.default_rng(seed + 100)
    n = int(rng.integers(30, 120))
    src = rng.integers(0, CAP, n)
    dst = rng.integers(0, CAP, n)
    val = rng.integers(1, 20, n)
    tim = np.sort(rng.integers(0, 5000, n))
    k = int(rng.integers(2, 4))
    batch = int(rng.choice([4, 8]))

    # host-modellable chain ops over (s, d, v) tuples
    host_ops = {
        "rev": lambda es: [(d, s, v) for s, d, v in es],
        "fe_mod": lambda es: [(s, d, v) for s, d, v in es if (s + d) % 3 != 0],
        "fe_ne": lambda es: [(s, d, v) for s, d, v in es if s != d],
    }
    stream_ops = {
        "rev": lambda st: st.reverse(),
        "fe_mod": lambda st: st.filter_edges(lambda a, b, v: (a + b) % 3 != 0),
        "fe_ne": lambda st: st.filter_edges(lambda a, b, v: a != b),
    }
    names = [
        list(host_ops)[i]
        for i in rng.choice(len(host_ops), rng.integers(0, 3))
    ]

    cfg = StreamConfig(vertex_capacity=CAP, batch_size=batch)
    stream = EdgeStream.from_collection(
        [
            (int(s), int(d), int(v), int(t))
            for s, d, v, t in zip(src, dst, val, tim)
        ],
        cfg,
        batch_size=batch,
        with_time=True,
    )
    for nm in names:
        stream = stream_ops[nm](stream)
    got = sorted(
        tuple(r)
        for r in stream.slice(k * 1000, EdgeDirection.OUT, slide_ms=1000)
        .reduce_on_edges(lambda a, b: a + b)
        .collect()
    )

    # host model: chain, then sliding windows over 1000ms panes
    es = [
        (int(s), int(d), int(v), int(t))
        for s, d, v, t in zip(src, dst, val, tim)
    ]
    chained = [(s, d, v) for s, d, v, _ in es]
    times = [t for _, _, _, t in es]
    for nm in names:
        # reverse keeps positions; filters drop positions (and their times)
        if nm == "rev":
            chained = host_ops[nm](chained)
        else:
            if nm == "fe_mod":
                sel = [(s + d) % 3 != 0 for s, d, v in chained]
            else:
                sel = [s != d for s, d, v in chained]
            chained = [e for e, m in zip(chained, sel) if m]
            times = [t for t, m in zip(times, sel) if m]
    pane_of = [t // 1000 for t in times]
    want = []
    if pane_of:
        for wid in range(min(pane_of), max(pane_of) + k):
            sums = {}
            for (s, d, v), p in zip(chained, pane_of):
                if wid - k + 1 <= p <= wid:
                    sums[s] = sums.get(s, 0) + v
            want.extend(sums.items())
    assert got == sorted(want), (seed, names, k)

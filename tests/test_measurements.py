"""Measurement CLIs (the pom.xml ghost measurement jars, made real).

Small CPU-sized runs asserting each subcommand's JSON contract and sanity of
the reported values (degree conservation, known bipartite verdicts).
"""

import json

import numpy as np
import pytest

from gelly_streaming_tpu.examples import measurements


def _run(argv, capsys):
    measurements.main(argv)
    return json.loads(capsys.readouterr().out.strip().splitlines()[-1])


def test_degrees_conserves_edge_endpoints(capsys):
    out = _run(
        ["degrees", "--edges", "4096", "--vertices", "512", "--batch", "512"],
        capsys,
    )
    assert out["workload"] == "degrees"
    assert out["edges_per_sec"] > 0
    assert out["edges_folded"] == 4096
    # every folded edge contributes exactly two endpoint counts
    assert out["degree_total"] == 2 * out["edges_folded"]
    # the measured Flink-shaped denominator folds the same seeded stream
    # through per-key HashMap state; its counts must match the device fold
    if "flink_proxy_eps" in out:
        assert out["flink_proxy_eps"] > 0
        assert out["flink_proxy_counts_ok"] is True


def test_degrees_small_edges_shrink_batch(capsys):
    """--edges below --batch must still meter (batch auto-shrinks to keep a
    warmup batch plus at least one measured batch)."""
    out = _run(
        ["degrees", "--edges", "100", "--vertices", "64", "--batch", "512"],
        capsys,
    )
    assert out["edges_per_sec"] > 0
    assert out["edges_folded"] == 100
    assert out["degree_total"] == 200


def test_degrees_trace_reports_emission_rate(capsys):
    """--trace drains the full (vertex, degree) record trace through the
    pipelined emission plane: exactly 2 records per edge, rate reported."""
    out = _run(
        [
            "degrees", "--edges", "4096", "--vertices", "512",
            "--batch", "1024", "--trace",
        ],
        capsys,
    )
    assert out["trace_records"] == 2 * 4096
    assert out["trace_records_per_sec"] > 0
    assert out["trace_host_gbps"] > 0


def test_bipartiteness_random_dense_is_odd(capsys):
    out = _run(
        ["bipartiteness", "--edges", "4096", "--vertices", "64", "--batch", "512"],
        capsys,
    )
    assert out["workload"] == "bipartiteness"
    # a dense random graph on 64 vertices contains odd cycles w.h.p.
    assert out["bipartite"] is False


def test_triangles_reports_latency_percentiles(capsys):
    out = _run(
        [
            "triangles",
            "--edges", "2048",
            "--windows", "2",
            "--pane-vertices", "128",
        ],
        capsys,
    )
    assert out["workload"] == "triangles"
    assert out["windows"] == 2
    assert out["triangles_total"] > 0
    assert out["p50_window_ms"] > 0
    assert out["p95_window_ms"] >= out["p50_window_ms"]


def test_requires_subcommand():
    with pytest.raises(SystemExit):
        measurements.main([])


def test_matching_measurement(capsys):
    from gelly_streaming_tpu.examples.measurements import main

    main(["matching", "--edges", "512", "--vertices", "128", "--batch", "128"])
    out = json.loads(capsys.readouterr().out.strip())
    assert out["workload"] == "matching"
    assert out["edges_streamed"] == 512
    assert out["matched_edges"] > 0 and out["events"] >= out["matched_edges"]
    assert out["net_runtime_s"] > 0


def test_spanner_measurement(capsys):
    from gelly_streaming_tpu.examples.measurements import main

    main(["spanner", "--edges", "2048", "--vertices", "64", "--batch", "512"])
    out = json.loads(capsys.readouterr().out.strip())
    assert out["workload"] == "spanner"
    assert 0 < out["spanner_edges"] <= 2048
    assert out["edges_per_sec"] > 0


def test_spanner_body_calibration(capsys):
    """--body both runs BOTH exact distance bodies on the same stream
    (VERDICT r4 item 7): identical spanners, both rates reported, and the
    ball_cost crossover's pick recorded against the measured winner."""
    from gelly_streaming_tpu.examples.measurements import main

    main([
        "spanner", "--edges", "2048", "--vertices", "128", "--batch", "512",
        "--max-degree", "16", "--k", "3", "--body", "both",
    ])
    out = json.loads(capsys.readouterr().out.strip())
    assert out["workload"] == "spanner_body_calibration"
    assert out["bodies_agree"] is True
    assert out["balls_eps"] > 0 and out["bfs_eps"] > 0
    assert out["measured_winner"] in ("balls", "bfs")
    assert out["analytical_pick"] in ("balls", "bfs")


def test_sage_measurement(capsys):
    out = _run(
        [
            "sage",
            "--edges", "2048",
            "--vertices", "256",
            "--windows", "2",
            "--features", "32",
            "--out-features", "16",
            "--max-degree", "8",
        ],
        capsys,
    )
    assert out["workload"] == "graphsage"
    assert out["windows"] == 2
    assert out["edges_per_sec"] > 0
    assert out["embeddings_per_sec"] > 0
    assert out["device_p50_pane_ms"] > 0
    assert out["feature_gather_gbps"] > 0


def test_replay_measurement(capsys):
    out = _run(
        ["replay", "--edges", "4096", "--vertices", "512", "--batch", "1024"],
        capsys,
    )
    assert out["workload"] == "wire_replay_cc"
    assert out["edges"] == 4096
    assert out["replay_eps"] > 0 and out["pack_eps"] > 0
    # capacity 512 << batch 1024: EF40 (~2.7 B/edge) must win over the
    # 4 B/edge width-2 fixed pack — pins the encoding selection
    assert out["bytes_per_edge"] < 3


def test_pagerank_measurement(capsys):
    out = _run(
        [
            "pagerank",
            "--edges", "2048",
            "--vertices", "256",
            "--windows", "2",
        ],
        capsys,
    )
    assert out["workload"] == "pagerank"
    assert out["windows"] == 2
    assert out["edges_per_sec"] > 0
    assert out["device_iters"] > 1
    assert out["device_ms_per_iter"] > 0


@pytest.mark.parametrize("workload", ["sssp", "kcore"])
def test_sssp_kcore_measurements(capsys, workload):
    out = _run(
        [workload, "--edges", "1024", "--vertices", "128", "--windows", "2"],
        capsys,
    )
    assert out["workload"] == workload
    assert out["windows"] == 2
    assert out["edges_per_sec"] > 0

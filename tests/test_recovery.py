"""Failure recovery: crash mid-stream, restart from checkpoint, exact state.

The supervisor (utils/recovery.py) rebuilds the pipeline after a failure; the
aggregation checkpoint now carries the stream position, so the rebuilt run
replays the source from the beginning and skips already-folded windows —
summary state stays exactly-once even for non-idempotent folds (sums), which
double-counting would corrupt.
"""

import os

import jax.numpy as jnp
import numpy as np
import pytest

from gelly_streaming_tpu.core.config import StreamConfig
from gelly_streaming_tpu.core.aggregation import SummaryBulkAggregation
from gelly_streaming_tpu.core.stream import EdgeStream
from gelly_streaming_tpu.library.connected_components import ConnectedComponents
from gelly_streaming_tpu.utils.recovery import run_supervised

CFG = StreamConfig(vertex_capacity=16, max_degree=16)

EDGES_T = [
    (1, 2, 1.0, 10),
    (3, 4, 2.0, 110),
    (2, 3, 4.0, 210),
    (5, 6, 8.0, 310),
]


class EdgeValueSum(SummaryBulkAggregation):
    """Non-idempotent fold: re-folding any window inflates the sum."""

    def initial_state(self, cfg):
        return jnp.zeros((), jnp.float32)

    def update(self, state, src, dst, val, mask):
        return state + jnp.sum(jnp.where(mask, val, 0.0))

    def combine(self, a, b):
        return a + b

    def transform(self, state):
        return float(state)


def _flaky_source(crash_on_attempt, crash_at_batch=None):
    """Source factory: raises mid-stream on designated attempts, then replays
    the FULL stream on later attempts (recovery's replay contract).

    ``crash_on_attempt`` is a set (all crash at ``crash_at_batch``) or a dict
    attempt-number -> crash batch.
    """
    attempts = {"n": 0}
    plan = (
        crash_on_attempt
        if isinstance(crash_on_attempt, dict)
        else {a: crash_at_batch for a in crash_on_attempt}
    )

    def make_stream():
        attempts["n"] += 1
        crash_at = plan.get(attempts["n"])

        def factory():
            for i, e in enumerate(EDGES_T):
                if crash_at is not None and i == crash_at:
                    raise IOError("source died")
                yield EdgeStream.from_collection(
                    [e], CFG, batch_size=1, with_time=True
                ).batches().__next__()

        return EdgeStream.from_batches(factory, CFG)

    return make_stream, attempts


@pytest.mark.parametrize("agg_cls", [EdgeValueSum, ConnectedComponents])
def test_crash_and_recover_matches_uninterrupted(tmp_path, agg_cls):
    ckpt = os.path.join(str(tmp_path), "state.npz")
    make_source, attempts = _flaky_source({1}, crash_at_batch=3)

    records = list(
        run_supervised(
            lambda: agg_cls(window_ms=100).run(
                make_source(), checkpoint_path=ckpt
            ),
            max_restarts=2,
        )
    )
    assert attempts["n"] == 2  # crashed once, recovered once

    full = agg_cls(window_ms=100).run(
        EdgeStream.from_collection(EDGES_T, CFG, batch_size=1, with_time=True)
    )
    expected_final = full.collect()[-1]
    assert str(records[-1][0]) == str(expected_final[0])
    if agg_cls is EdgeValueSum:
        # exactly-once: any double-folded window would inflate the sum
        assert records[-1][0] == 15.0


def test_exhausted_restarts_propagate(tmp_path):
    ckpt = os.path.join(str(tmp_path), "state.npz")
    # crashes on every attempt at the FIRST batch: no progress, budget exhausts
    make_source, attempts = _flaky_source({1, 2, 3, 4, 5}, crash_at_batch=0)
    with pytest.raises(IOError, match="source died"):
        list(
            run_supervised(
                lambda: EdgeValueSum(window_ms=100).run(
                    make_source(), checkpoint_path=ckpt
                ),
                max_restarts=2,
            )
        )
    assert attempts["n"] == 3  # initial + 2 restarts


def test_progress_resets_restart_budget(tmp_path):
    """Each crash at a later point is a fresh failure, not a wedged stream."""
    ckpt = os.path.join(str(tmp_path), "state.npz")
    # attempt 1 crashes at batch 2, attempt 2 later at batch 3 (after having
    # emitted a new window), attempt 3 completes
    make_source, attempts = _flaky_source({1: 2, 2: 3})
    records = list(
        run_supervised(
            lambda: EdgeValueSum(window_ms=100).run(
                make_source(), checkpoint_path=ckpt
            ),
            max_restarts=1,  # would exhaust without the progress reset
        )
    )
    assert attempts["n"] == 3
    assert records[-1][0] == 15.0


def test_untimed_global_pane_does_not_double_fold(tmp_path):
    """An unchanged replay of an untimed stream must not re-fold the single
    global pane into the restored summary."""
    ckpt = os.path.join(str(tmp_path), "state.npz")
    untimed = [(1, 2, 1.0), (3, 4, 2.0)]

    def run_once():
        stream = EdgeStream.from_collection(untimed, CFG, batch_size=1)
        return EdgeValueSum().run(stream, checkpoint_path=ckpt).collect()

    first = run_once()
    assert first[-1][0] == 3.0
    second = run_once()  # full replay with the checkpoint present
    # the global pane was already folded: nothing new to emit, and the
    # summary must NOT become 6.0
    assert second == []


def test_legacy_bare_summary_checkpoint_still_restores(tmp_path):
    """Pre-position checkpoints (bare summary pytree) keep their old
    contract: restore the summary, caller feeds only the unprocessed tail."""
    from gelly_streaming_tpu.utils.checkpoint import save_state

    ckpt = os.path.join(str(tmp_path), "state.npz")
    save_state(ckpt, jnp.asarray(7.0, jnp.float32))  # legacy layout
    stream = EdgeStream.from_collection(
        EDGES_T[2:], CFG, batch_size=1, with_time=True
    )
    out = EdgeValueSum(window_ms=100).run(stream, checkpoint_path=ckpt).collect()
    assert out[-1][0] == 7.0 + 4.0 + 8.0


def test_emission_precedes_snapshot(tmp_path):
    """A crash right after a yield (before the snapshot that follows the
    NEXT window) re-emits: windows are at-least-once, never dropped."""
    ckpt = os.path.join(str(tmp_path), "state.npz")
    make_source, attempts = _flaky_source({}, None)

    seen = []
    gen = iter(
        EdgeValueSum(window_ms=100).run(make_source(), checkpoint_path=ckpt)
    )
    seen.append(next(gen))  # window 0 emitted...
    del gen  # ...and the consumer dies before ever resuming the generator

    # recovery replays: window 0 must appear again (its snapshot only lands
    # when the generator resumes after the yield, which never happened)
    out = EdgeValueSum(window_ms=100).run(
        make_source(), checkpoint_path=ckpt
    ).collect()
    assert seen[0][0] == 1.0
    assert [r[0] for r in out] == [1.0, 3.0, 7.0, 15.0]


def test_on_restart_hook_observes_failures(tmp_path):
    ckpt = os.path.join(str(tmp_path), "state.npz")
    make_source, _ = _flaky_source({1}, crash_at_batch=2)
    seen = []
    list(
        run_supervised(
            lambda: EdgeValueSum(window_ms=100).run(
                make_source(), checkpoint_path=ckpt
            ),
            max_restarts=2,
            on_restart=lambda n, e: seen.append((n, str(e))),
        )
    )
    assert seen == [(1, "source died")]


def test_total_restart_cap_binds_on_progress_then_crash():
    """A pipeline that re-emits a record then crashes deterministically used
    to reset the consecutive budget forever; the absolute cap now binds
    (ADVICE r1)."""
    import pytest

    from gelly_streaming_tpu.utils.recovery import run_supervised

    attempts = []

    def make_stream():
        attempts.append(1)

        def gen():
            yield ("progress",)  # resets the consecutive budget every time
            raise RuntimeError("deterministic crash after progress")

        return gen()

    with pytest.raises(RuntimeError):
        list(run_supervised(make_stream, max_restarts=2, max_total_restarts=5))
    assert len(attempts) == 6  # initial run + 5 restarts, then give up

"""The README quick-start must keep working verbatim (doc-rot guard)."""

from gelly_streaming_tpu import EdgeDirection, EdgeStream, StreamConfig
from gelly_streaming_tpu.library import ConnectedComponents


def test_quickstart_flow():
    cfg = StreamConfig(vertex_capacity=1 << 10, batch_size=1 << 6)
    stream = EdgeStream.from_collection([(1, 2), (2, 3), (5, 6)], cfg)

    degrees = stream.get_degrees().collect()
    assert (1, 1) in degrees and (3, 1) in degrees

    nv = stream.number_of_vertices().collect()
    assert nv[-1] == (5,)

    reduced = (
        stream.slice(1000, EdgeDirection.OUT)
        .fold_neighbors((0, 0), lambda acc, vid, nbr, val: (vid, acc[1] + 1))
        .collect()
    )
    assert len(reduced) == 3  # vertices 1, 2, 5 have out-neighbors

    outs = [c for (c,) in stream.aggregate(ConnectedComponents(window_ms=1000))]
    rendered = str(outs[-1])
    assert "1" in rendered and "5" in rendered

"""The README quick-start must keep working verbatim (doc-rot guard)."""

from gelly_streaming_tpu import EdgeDirection, EdgeStream, StreamConfig
from gelly_streaming_tpu.library import ConnectedComponents


def test_quickstart_flow():
    cfg = StreamConfig(vertex_capacity=1 << 10, batch_size=1 << 6)
    stream = EdgeStream.from_collection([(1, 2), (2, 3), (5, 6)], cfg)

    degrees = stream.get_degrees().collect()
    assert (1, 1) in degrees and (3, 1) in degrees

    nv = stream.number_of_vertices().collect()
    assert nv[-1] == (5,)

    reduced = (
        stream.slice(1000, EdgeDirection.OUT)
        .fold_neighbors((0, 0), lambda acc, vid, nbr, val: (vid, acc[1] + 1))
        .collect()
    )
    assert len(reduced) == 3  # vertices 1, 2, 5 have out-neighbors

    outs = [c for (c,) in stream.aggregate(ConnectedComponents(window_ms=1000))]
    rendered = str(outs[-1])
    assert "1" in rendered and "5" in rendered


def test_quickstart_sliding_and_out_of_order():
    timed = [(1, 2, 1.0, 100), (2, 3, 1.0, 1500), (1, 3, 1.0, 800)]
    cfg_t = StreamConfig(vertex_capacity=1 << 10, out_of_orderness_ms=1000)
    tstream = EdgeStream.from_collection(
        timed, cfg_t, batch_size=1, with_time=True
    )
    lates = []
    tstream.on_late(lambda s, d, v, t: lates.append(len(s)))
    recs = sorted(
        tuple(r)
        for r in tstream.slice(2000, EdgeDirection.OUT, slide_ms=1000)
        .reduce_on_edges(lambda a, b: a + b)
        .collect()
    )
    # batch_size=1: the t=1500 edge arrives BEFORE the t=800 straggler, so
    # the watermark (1500 - 1000) is live when the straggler lands — inside
    # the bound, it still joins pane 0.  windows (k=2): 0:{p0}, 1:{p0,p1}
    assert recs == [(1, 2.0), (1, 2.0), (2, 1.0), (2, 1.0)]
    assert lates == []

    # and with bound 0 the same stream DROPS the straggler to the late sink
    cfg0 = StreamConfig(vertex_capacity=1 << 10)
    s0 = EdgeStream.from_collection(timed, cfg0, batch_size=1, with_time=True)
    lates0 = []
    s0.on_late(lambda s, d, v, t: lates0.append(len(s)))
    recs0 = sorted(
        tuple(r)
        for r in s0.slice(2000, EdgeDirection.OUT, slide_ms=1000)
        .reduce_on_edges(lambda a, b: a + b)
        .collect()
    )
    assert lates0 == [1]
    assert recs0 == [(1, 1.0), (1, 1.0), (2, 1.0), (2, 1.0)]

"""Ingestion-time pane cutting for untimed streams (VERDICT r3 missing #2).

The reference's DEFAULT mode is ingestion-time tumbling windows with running
emission (SimpleEdgeStream.java:69-73; ConnectedComponentsExample.java:65-67
prints per window).  Without the knobs an untimed stream is one global pane
flushed at end-of-stream — an infinite source would never emit.  These tests
pin the arrival-count cut (deterministic), the wall-clock cut (injected
clock), running emission over an unbounded generator, checkpoint/resume on
synthetic window ids, and that finite-stream goldens are unchanged.
"""

import numpy as np
import pytest

from gelly_streaming_tpu.core.config import StreamConfig
from gelly_streaming_tpu.core.stream import EdgeStream
from gelly_streaming_tpu.core.types import EdgeBatch
from gelly_streaming_tpu.core.windows import assign_ingestion_windows
from gelly_streaming_tpu.library.connected_components import ConnectedComponents


def _batches(chunks):
    def factory():
        for s, d in chunks:
            yield EdgeBatch.from_arrays(
                np.asarray(s, np.int32), np.asarray(d, np.int32)
            )

    return factory


def test_arrival_count_panes_split_mid_batch():
    chunks = [([1, 2, 3], [2, 3, 4]), ([5, 6], [6, 7])]
    panes = list(
        assign_ingestion_windows(_batches(chunks)(), every_edges=2)
    )
    # 5 edges at 2/pane -> panes of 2, 2, 1 with ascending ids
    assert [p.window_id for p in panes] == [0, 1, 2]
    assert [p.num_edges for p in panes] == [2, 2, 1]
    assert list(panes[0].src) == [1, 2] and list(panes[1].src) == [3, 5]
    assert all(p.max_timestamp == -1 for p in panes)


def test_wall_clock_panes_cut_at_batch_boundaries():
    now = [0.0]
    chunks = [([1], [2]), ([3], [4]), ([5], [6])]

    def clock():
        now[0] += 0.6  # 600 ms between batch arrivals
        return now[0]

    panes = list(
        assign_ingestion_windows(
            _batches(chunks)(), every_ms=1000, clock=clock
        )
    )
    # arrivals at 0, 600, 1200 ms relative to the first -> windows 0, 0, 1
    assert [p.window_id for p in panes] == [0, 1]
    assert [p.num_edges for p in panes] == [2, 1]


def test_unbounded_generator_emits_running_components():
    """An infinite untimed source yields one running summary per pane —
    WITHOUT reaching end-of-stream (the generator is never exhausted)."""
    from gelly_streaming_tpu.io.sources import unbounded_generated_stream

    cfg = StreamConfig(
        vertex_capacity=64, batch_size=8, ingest_window_edges=16
    )
    stream = unbounded_generated_stream(cfg, num_vertices=32, max_batches=None)
    out = iter(stream.aggregate(ConnectedComponents()))
    first = next(out)[0]
    second = next(out)[0]
    third = next(out)[0]
    # running merge: component count is non-increasing as edges accumulate
    n1 = len(first.components())
    n3 = len(third.components())
    assert n3 <= n1
    out.close()


def test_arrival_count_panes_fuzz_partition_exactly():
    """Property fuzz: over random batch/pane geometries, the emitted panes
    are EXACTLY the arrival stream re-chunked at every_edges — same edges,
    same order, contiguous ascending ids, all full except the last."""
    rng = np.random.default_rng(17)
    for _ in range(25):
        n_batches = int(rng.integers(0, 6))
        sizes = [int(rng.integers(0, 9)) for _ in range(n_batches)]
        every = int(rng.integers(1, 8))
        chunks = []
        base = 0
        for s in sizes:
            chunks.append(
                (
                    np.arange(base, base + s, dtype=np.int64) % 64,
                    np.arange(base, base + s, dtype=np.int64) * 3 % 64,
                )
            )
            base += s
        panes = list(
            assign_ingestion_windows(_batches(chunks)(), every_edges=every)
        )
        all_src = np.concatenate(
            [c[0] for c in chunks] or [np.empty(0, np.int64)]
        )
        all_dst = np.concatenate(
            [c[1] for c in chunks] or [np.empty(0, np.int64)]
        )
        total = len(all_src)
        want_panes = -(-total // every) if total else 0
        assert [p.window_id for p in panes] == list(range(want_panes))
        got_src = np.concatenate(
            [p.src for p in panes] or [np.empty(0, np.int64)]
        )
        got_dst = np.concatenate(
            [p.dst for p in panes] or [np.empty(0, np.int64)]
        )
        assert np.array_equal(got_src, all_src), (sizes, every)
        assert np.array_equal(got_dst, all_dst), (sizes, every)
        for p in panes[:-1]:
            assert p.num_edges == every, (sizes, every)
        if panes:
            assert panes[-1].num_edges == total - every * (want_panes - 1)


def test_ingest_panes_match_global_pane_final_summary():
    """Finite stream: the LAST running summary equals the single-global-pane
    result (same edges, same order-free fold) and finite goldens without the
    knob are unchanged."""
    rng = np.random.default_rng(3)
    src = rng.integers(0, 64, 200).astype(np.int32)
    dst = rng.integers(0, 64, 200).astype(np.int32)
    plain_cfg = StreamConfig(vertex_capacity=64, batch_size=32)
    ingest_cfg = StreamConfig(
        vertex_capacity=64, batch_size=32, ingest_window_edges=48
    )
    plain = (
        EdgeStream.from_arrays(src, dst, plain_cfg)
        .aggregate(ConnectedComponents())
        .collect()
    )
    assert len(plain) == 1  # single global pane -> one emission
    windowed = (
        EdgeStream.from_arrays(src, dst, ingest_cfg)
        .aggregate(ConnectedComponents())
        .collect()
    )
    assert len(windowed) == -(-200 // 48)  # one emission per pane
    assert windowed[-1][0].components() == plain[-1][0].components()


def test_ingest_panes_checkpoint_resume(tmp_path):
    import os

    rng = np.random.default_rng(5)
    src = rng.integers(0, 64, 160).astype(np.int32)
    dst = rng.integers(0, 64, 160).astype(np.int32)
    cfg = StreamConfig(
        vertex_capacity=64, batch_size=32, ingest_window_edges=40
    )
    ckpt = os.path.join(str(tmp_path), "ingest_cc.npz")
    stream = lambda: EdgeStream.from_arrays(src, dst, cfg)  # noqa: E731
    full = [
        str(r[0])
        for r in stream().aggregate(ConnectedComponents()).collect()
    ]
    it = iter(stream().aggregate(ConnectedComponents(), checkpoint_path=ckpt))
    next(it)
    next(it)
    it.close()
    resumed = [
        str(r[0])
        for r in stream()
        .aggregate(ConnectedComponents(), checkpoint_path=ckpt)
        .collect()
    ]
    # window 0 snapshot landed; window 1's emission re-emits (at-least-once)
    assert resumed == full[1:]


def test_ingest_knobs_validated():
    with pytest.raises(ValueError, match="only one"):
        StreamConfig(ingest_window_edges=4, ingest_window_ms=100)
    with pytest.raises(ValueError, match=">= 0"):
        StreamConfig(ingest_window_edges=-1)
    with pytest.raises(ValueError, match="exactly one"):
        list(assign_ingestion_windows(iter([]), 0, 0))


def test_unbounded_cc_example_prints_per_window(capsys):
    from gelly_streaming_tpu.examples.connected_components import main

    main(["--unbounded=4", "--ingest-window=1024"])
    lines = [
        line
        for line in capsys.readouterr().out.splitlines()
        if line and line[0].isdigit()
    ]
    # 4 batches x 4096 edges at 1024/pane = 16 panes, each printing >= 1
    # component row (vs exactly one print for the whole stream without the
    # ingest knob — the running-emission UX of the reference's example)
    assert len(lines) >= 16


def test_mesh_runner_ingest_panes_match_simulated():
    """Ingestion-time panes flow through the sharded runner identically."""
    rng = np.random.default_rng(7)
    src = rng.integers(0, 64, 128).astype(np.int32)
    dst = rng.integers(0, 64, 128).astype(np.int32)
    single = StreamConfig(vertex_capacity=64, batch_size=16, ingest_window_edges=24)
    sharded = StreamConfig(
        vertex_capacity=64, batch_size=16, num_shards=8, ingest_window_edges=24
    )
    expect = [
        str(r[0])
        for r in EdgeStream.from_arrays(src, dst, single)
        .aggregate(ConnectedComponents())
        .collect()
    ]
    got = [
        str(r[0])
        for r in EdgeStream.from_arrays(src, dst, sharded)
        .aggregate(ConnectedComponents())
        .collect()
    ]
    assert got == expect


def test_wall_clock_panes_refuse_checkpointing(tmp_path):
    import os

    cfg = StreamConfig(vertex_capacity=64, batch_size=8, ingest_window_ms=100)
    stream = EdgeStream.from_collection([(1, 2, 0.0)], cfg, batch_size=2)
    with pytest.raises(ValueError, match="not\\s+replay-deterministic"):
        stream.aggregate(
            ConnectedComponents(),
            checkpoint_path=os.path.join(str(tmp_path), "x.npz"),
        ).collect()


def test_from_wire_tail_rejects_wrapping_ids():
    """Tail bounds must be checked BEFORE the int32 cast (review finding:
    a 64-bit id that wraps into range must not pass)."""
    from gelly_streaming_tpu.io import wire

    cfg = StreamConfig(vertex_capacity=64, batch_size=8)
    ok = wire.pack_edges(
        np.array([1] * 8, np.int32), np.array([2] * 8, np.int32), 2
    )
    with pytest.raises(ValueError, match="tail vertex ids"):
        EdgeStream.from_wire(
            [ok], 8, 2, cfg,
            tail=(
                np.array([(1 << 32) + 5], np.int64),
                np.array([1], np.int64),
            ),
        )


def test_cc_example_ingest_window_applies_to_generated_input(capsys):
    from gelly_streaming_tpu.examples.connected_components import main

    main(["--ingest-window=200"])
    rows = [
        line
        for line in capsys.readouterr().out.splitlines()
        if line and line[0].isdigit()
    ]
    main([])
    rows_plain = [
        line
        for line in capsys.readouterr().out.splitlines()
        if line and line[0].isdigit()
    ]
    # 1000 generated edges at 200/pane -> 5 running emissions vs 1
    assert len(rows) > len(rows_plain)


def test_ingest_panes_stay_on_wire_fast_path_when_aligned(monkeypatch):
    """ingest_window_edges that divides the batch size keeps the stream ON
    the packed-wire fast path with running emission at pane boundaries —
    the unbounded-source UX at full wire speed; outputs match the windowed
    runtime record for record."""
    import gelly_streaming_tpu.core.aggregation as agg_mod

    rng = np.random.default_rng(13)
    src = rng.integers(0, 64, 200).astype(np.int32)
    dst = rng.integers(0, 64, 200).astype(np.int32)

    calls = []
    orig = agg_mod.SummaryAggregation._wire_records

    def spy(self, *a, **k):
        calls.append("wire")
        return orig(self, *a, **k)

    monkeypatch.setattr(agg_mod.SummaryAggregation, "_wire_records", spy)

    # aligned: pane = 64 edges = 2 batches of 32 -> fast path, running panes
    aligned = StreamConfig(
        vertex_capacity=64, batch_size=32, ingest_window_edges=64
    )
    fast = [
        str(r[0])
        for r in EdgeStream.from_arrays(src, dst, aligned)
        .aggregate(ConnectedComponents())
        .collect()
    ]
    assert calls == ["wire"]
    # 200 edges at 64/pane -> panes at 64, 128, 192 + final for the tail 8
    assert len(fast) == 4

    # reference: force the windowed runtime on the same config
    calls.clear()
    monkeypatch.setattr(
        agg_mod.SummaryAggregation, "_wire_eligible", lambda self, s: False
    )
    slow = [
        str(r[0])
        for r in EdgeStream.from_arrays(src, dst, aligned)
        .aggregate(ConnectedComponents())
        .collect()
    ]
    # windowed panes: 64, 64, 64, 8 -> same running records
    assert fast == slow

    # non-aligned pane size must FALL BACK to the windowed runtime
    monkeypatch.undo()  # removes the _wire_eligible override AND the spy...
    monkeypatch.setattr(agg_mod.SummaryAggregation, "_wire_records", spy)
    calls.clear()  # ...so re-install the spy: the path assertion must be real
    odd = StreamConfig(vertex_capacity=64, batch_size=32, ingest_window_edges=48)
    out = (
        EdgeStream.from_arrays(src, dst, odd)
        .aggregate(ConnectedComponents())
        .collect()
    )
    assert calls == []  # not on the fast path
    assert len(out) == -(-200 // 48)


def test_ingest_panes_wire_fast_path_exact_boundary(monkeypatch):
    """A stream ending exactly on a pane boundary emits once per pane, no
    duplicate final record."""
    rng = np.random.default_rng(19)
    src = rng.integers(0, 64, 128).astype(np.int32)
    dst = rng.integers(0, 64, 128).astype(np.int32)
    cfg = StreamConfig(vertex_capacity=64, batch_size=32, ingest_window_edges=64)
    out = (
        EdgeStream.from_arrays(src, dst, cfg)
        .aggregate(ConnectedComponents())
        .collect()
    )
    assert len(out) == 2  # 128 edges, 64/pane, boundary-exact


def test_ingest_panes_fast_path_covers_replay_source():
    """from_wire replay streams with batch-aligned panes also stay on the
    fast path with running emission (eligibility reads the packed batch)."""
    from gelly_streaming_tpu.io import wire
    from gelly_streaming_tpu.library.connected_components import (
        ConnectedComponents,
    )

    rng = np.random.default_rng(29)
    src = rng.integers(0, 64, 256).astype(np.int32)
    dst = rng.integers(0, 64, 256).astype(np.int32)
    width = wire.width_for_capacity(64)
    bufs, tail = wire.pack_stream(src, dst, 32, width)
    assert tail is None
    cfg = StreamConfig(vertex_capacity=64, batch_size=32, ingest_window_edges=64)
    agg = ConnectedComponents()
    stream = EdgeStream.from_wire(bufs, 32, width, cfg)
    assert agg._wire_eligible(stream)
    out = stream.aggregate(agg).collect()
    assert len(out) == 4  # 256 edges at 64/pane, boundary-exact
    # final pane equals the plain single-emission run
    plain = (
        EdgeStream.from_wire(
            bufs, 32, width, StreamConfig(vertex_capacity=64, batch_size=32)
        )
        .aggregate(ConnectedComponents())
        .collect()
    )
    assert out[-1][0].components() == plain[-1][0].components()

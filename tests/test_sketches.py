"""Sketch summary family (ISSUE 19): fixed-tiny-state approximate
descriptors as ORDINARY summaries on every existing plane.

The contracts under test:

* ACCURACY — each sketch's estimate lands within its declared (eps, delta)
  of the exact oracle on seeded hub-heavy streams (deterministic: seeded
  edges + salted hashing make every estimate a pinned constant, so these
  are equality-class assertions, not statistical ones).
* MERGEABILITY — every sketch state is a commutative monoid: partial folds
  combine to the same bits in any order, and the owner-sharded plane
  (S = 8 modulo register blocks) emits BIT-IDENTICAL records to the
  replicated oracle with zero sketch-specific machinery.
* RECOVERY — positional checkpoints + kill-mid-stream resume parity, the
  same at-least-once story the exact summaries pin.
* ELASTICITY — ``reshard_summary(..., rows="auto")`` re-routes the
  register blocks S -> 2S -> S bit-exactly even though the leaves carry
  DIFFERENT pow2 row counts.
* 0-RECOMPILE — 50 same-width panes and 1 -> 16-job fused tenancy drift
  compile nothing after warmup (pow2 register shapes + shared
  ``cache_token`` per contract).
* ADMISSION — ``admission_nbytes`` prices the emission-time residue (the
  count-min top-k's O(C) gathered view) on top of the persistent KBs, and
  the manager refuses at exactly that byte figure.
* SERVING — ``summary: <kind>`` + ``eps``/``delta`` knobs ride job specs;
  malformed contracts refuse loudly at admission with a typed error.
"""

import dataclasses
import os

import numpy as np
import pytest

from gelly_streaming_tpu.core import compile_cache
from gelly_streaming_tpu.core.config import RuntimeConfig, StreamConfig
from gelly_streaming_tpu.core.sharded_state import reshard_summary
from gelly_streaming_tpu.core.stream import EdgeStream
from gelly_streaming_tpu.library.sketches import (
    SKETCH_KINDS,
    CountMinHeavyHitters,
    HLLDegreeSummary,
    SketchParamError,
    SketchTriangleCount,
    make_sketch,
)

pytestmark = pytest.mark.timeout_cap(300)

CAP = 64
S = 8


def _cfg(**kw):
    base = dict(
        vertex_capacity=CAP, batch_size=64, num_shards=S, window_ms=1000
    )
    base.update(kw)
    return StreamConfig(**base)


def _both(cfg):
    return (
        dataclasses.replace(cfg, sharded_state=1),
        dataclasses.replace(cfg, sharded_state=0),
    )


def _rand_edges(n, seed=0, cap=CAP):
    rng = np.random.default_rng(seed)
    return (
        rng.integers(0, cap, n).astype(np.int32),
        rng.integers(0, cap, n).astype(np.int32),
    )


def _skewed_edges(n, cap, seed=7):
    """Hub-heavy, community-clustered edges (the bench's skew model)."""
    rng = np.random.default_rng(seed)
    comm = max(cap >> 14, 64)
    cbase = ((cap * rng.random(n) ** 2).astype(np.int64) // comm) * comm
    s = cbase + (comm * rng.random(n) ** 2).astype(np.int64)
    d = cbase + (comm * rng.random(n) ** 4).astype(np.int64)
    return (s % cap).astype(np.int32), (d % cap).astype(np.int32)


def _timed_edges(n, seed=0, span_ms=3000, cap=CAP):
    rng = np.random.default_rng(seed)
    t = np.sort(rng.integers(0, span_ms, n)).astype(np.int64)
    s, d = _rand_edges(n, seed, cap)
    return [(int(s[i]), int(d[i]), 0.0, int(t[i])) for i in range(n)]


def _leaves(x):
    import jax

    return [np.asarray(l) for l in jax.tree.leaves(x)]


def _records_equal(a, b):
    assert len(a) == len(b)
    for ra, rb in zip(a, b):
        la, lb = _leaves(ra), _leaves(rb)
        assert len(la) == len(lb)
        for x, y in zip(la, lb):
            assert np.array_equal(x, y)


# ---------------------------------------------------------------------------
# accuracy: estimates within the declared (eps, delta) of exact oracles


def test_hll_degree_within_contract():
    cap, n = 4096, 20_000
    src, dst = _rand_edges(n, seed=5, cap=cap)
    cfg = StreamConfig(
        vertex_capacity=cap, batch_size=2048, ingest_window_edges=n
    )
    agg = HLLDegreeSummary(eps=0.05, delta=0.05)
    recs = EdgeStream.from_arrays(src, dst, cfg).aggregate(agg).collect()
    v_est = float(np.asarray(recs[-1][0]))
    e_est = float(np.asarray(recs[-1][1]))
    exact_v = len(np.unique(np.concatenate([src, dst])))
    lo, hi = np.minimum(src, dst), np.maximum(src, dst)
    exact_e = len(np.unique(lo.astype(np.int64) * cap + hi))
    assert abs(v_est - exact_v) / exact_v < agg.eps
    assert abs(e_est - exact_e) / exact_e < agg.eps


def test_cm_heavy_hitters_within_contract():
    cap, n = 512, 20_000
    src, dst = _skewed_edges(n, cap, seed=9)
    cfg = StreamConfig(
        vertex_capacity=cap, batch_size=2048, ingest_window_edges=n
    )
    agg = CountMinHeavyHitters(eps=0.01, delta=0.02, top_k=16)
    recs = EdgeStream.from_arrays(src, dst, cfg).aggregate(agg).collect()
    ids = np.asarray(recs[-1][0])
    est = np.asarray(recs[-1][1])
    deg = np.bincount(src, minlength=cap) + np.bincount(dst, minlength=cap)
    # count-min never undercounts, and the overcount stays within eps of
    # the total mass (2 endpoint increments per edge)
    assert np.all(est >= deg[ids])
    assert np.all(est - deg[ids] <= agg.eps * 2 * n)
    # the true heaviest vertices all surface in the top-k report
    true_top8 = set(np.argsort(deg)[-8:].tolist())
    assert true_top8 <= set(ids.tolist())


def test_triangle_estimate_within_contract():
    cap, n = 256, 40 << 10
    src, dst = _skewed_edges(n, cap, seed=7)
    cfg = StreamConfig(
        vertex_capacity=cap, batch_size=1 << 12, ingest_window_edges=n
    )
    agg = SketchTriangleCount(eps=0.05, delta=0.05)
    recs = EdgeStream.from_arrays(src, dst, cfg).aggregate(agg).collect()
    est = float(np.asarray(recs[-1][0]))
    adj = np.zeros((cap, cap), dtype=np.int64)
    keep = src != dst
    adj[src[keep], dst[keep]] = 1
    adj = np.maximum(adj, adj.T)
    exact = int(np.trace(adj @ adj @ adj)) // 6
    assert exact > 0
    assert abs(est - exact) / exact < agg.eps


# ---------------------------------------------------------------------------
# mergeability: commutative-monoid combine, order-free


@pytest.mark.parametrize("kind", SKETCH_KINDS)
def test_combine_order_free_bit_identity(kind):
    import jax.numpy as jnp

    agg = make_sketch(kind)
    cfg = _cfg()
    parts = []
    for seed in range(4):
        s, d = _rand_edges(128, seed=seed)
        st = agg.update(
            agg.initial_state(cfg),
            jnp.asarray(s),
            jnp.asarray(d),
            None,
            jnp.ones(len(s), bool),
        )
        parts.append(st)
    fwd = parts[0]
    for p in parts[1:]:
        fwd = agg.combine(fwd, p)
    rev = parts[3]
    for p in (parts[1], parts[2], parts[0]):
        rev = agg.combine(rev, p)
    for x, y in zip(_leaves(fwd), _leaves(rev)):
        assert np.array_equal(x, y)


@pytest.mark.parametrize("kind", SKETCH_KINDS)
@pytest.mark.parametrize("seed", [3, 11])
def test_sharded_emissions_match_replicated_oracle(kind, seed):
    """The tentpole claim: the owner-sharded plane (S = 8 modulo register
    blocks, slab exchange, lazy gather) emits records bit-identical to the
    replicated combine — with the sketch as a plain descriptor."""
    src, dst = _rand_edges(512, seed=seed)
    on, off = _both(_cfg())
    got = (
        EdgeStream.from_arrays(src, dst, on)
        .aggregate(make_sketch(kind))
        .collect()
    )
    exp = (
        EdgeStream.from_arrays(src, dst, off)
        .aggregate(make_sketch(kind))
        .collect()
    )
    _records_equal(got, exp)


# ---------------------------------------------------------------------------
# recovery: kill mid-stream, resume from the positional checkpoint


def test_windowed_kill_and_resume_parity(tmp_path):
    edges = _timed_edges(160, seed=12)
    on, off = _both(_cfg(batch_size=16))
    full = [
        _leaves(o)
        for o in EdgeStream.from_collection(
            edges, on, 16, with_time=True
        ).aggregate(HLLDegreeSummary())
    ]

    def killed_then_resumed(cfg, ckpt):
        it = iter(
            EdgeStream.from_collection(
                edges, cfg, 16, with_time=True
            ).aggregate(HLLDegreeSummary(), checkpoint_path=ckpt)
        )
        first_two = [_leaves(next(it)), _leaves(next(it))]
        it.close()
        assert os.path.exists(ckpt)
        resumed = [
            _leaves(o)
            for o in EdgeStream.from_collection(
                edges, cfg, 16, with_time=True
            ).aggregate(HLLDegreeSummary(), checkpoint_path=ckpt)
        ]
        return first_two, resumed

    def eq(a, b):
        assert len(a) == len(b)
        for la, lb in zip(a, b):
            for x, y in zip(la, lb):
                assert np.array_equal(x, y)

    first_on, resumed_on = killed_then_resumed(
        on, os.path.join(str(tmp_path), "sharded.npz")
    )
    first_off, resumed_off = killed_then_resumed(
        off, os.path.join(str(tmp_path), "replicated.npz")
    )
    eq(first_on, full[:2])
    # window 1's snapshot never landed (killed at the yield): it re-emits —
    # at-least-once, identical on both planes
    eq(resumed_on, full[1:])
    eq(resumed_on, resumed_off)


# ---------------------------------------------------------------------------
# elasticity: register blocks re-route S -> 2S -> S bit-exactly


@pytest.mark.parametrize("kind", SKETCH_KINDS)
def test_reshard_auto_round_trip(kind):
    import jax.numpy as jnp

    agg = make_sketch(kind)
    cfg = _cfg()
    s, d = _rand_edges(256, seed=2)
    state = agg.update(
        agg.initial_state(cfg),
        jnp.asarray(s),
        jnp.asarray(d),
        None,
        jnp.ones(len(s), bool),
    )
    spec = agg.sharded_state_spec(cfg)
    blocks_4 = spec.shard_summary(state, cfg, 4)
    # reshard == shard at the new geometry, leaf for leaf (the consistency
    # oracle reshard_summary's docstring pins), despite per-leaf row counts
    # differing across the pytree (sample rows vs registers vs cm cells)
    rerouted_8 = reshard_summary(blocks_4, cfg, 4, 8, rows="auto")
    direct_8 = spec.shard_summary(state, cfg, 8)
    for x, y in zip(_leaves(rerouted_8), _leaves(direct_8)):
        assert np.array_equal(x, y)
    back_4 = reshard_summary(rerouted_8, cfg, 8, 4, rows="auto")
    for x, y in zip(_leaves(back_4), _leaves(blocks_4)):
        assert np.array_equal(x, y)


def test_reshard_auto_rejects_uneven_geometry():
    agg = HLLDegreeSummary()
    cfg = _cfg()
    blocks = agg.sharded_state_spec(cfg).initial_shard_state(cfg, 4)
    with pytest.raises(ValueError, match="divisible"):
        reshard_summary(blocks, cfg, 4, 3, rows="auto")


# ---------------------------------------------------------------------------
# 0-recompile: same-width panes and fused tenancy drift retrace nothing


def test_zero_compiles_across_50_same_width_panes():
    cfg = StreamConfig(
        vertex_capacity=1 << 10, batch_size=256, ingest_window_edges=256
    )
    agg = HLLDegreeSummary()

    def run(windows):
        s, d = _rand_edges(windows * 256, seed=21, cap=1 << 10)
        return (
            EdgeStream.from_arrays(s, d, cfg)
            .aggregate(HLLDegreeSummary())
            .collect()
        )

    run(3)  # warmup: fold + transform executables land here
    compile_cache.reset_stats()
    out = run(50)
    assert len(out) == 50
    stats = compile_cache.stats()
    assert stats["compiles"] == 0
    assert stats["recompiles"] == 0
    del agg


def test_zero_compiles_across_fused_tenancy_drift():
    """1 -> 16 sketch jobs under the fused-dispatch manager: with the solo
    chain and every pow2 cohort row bucket warm, tenancy drift compiles
    NOTHING, let alone retraces.  Buckets are warmed explicitly (the
    test_fused_dispatch idiom) — cohort sizes at dispatch time depend on
    scheduler timing, so a run-shaped warmup can miss a bucket."""
    import jax.numpy as jnp

    from gelly_streaming_tpu.runtime import JobManager

    win = 256
    cfg = StreamConfig(
        vertex_capacity=1 << 10,
        batch_size=(win // 2) + 32,  # misaligned: the windowed plane runs
        ingest_window_edges=win,
        fused_dispatch=1,
    )
    datasets = [_rand_edges(4 * win, seed=30 + i, cap=1 << 10) for i in range(16)]

    def run(n_jobs):
        with JobManager(RuntimeConfig(max_jobs=16, fair_quantum=4)) as m:
            for i in range(n_jobs):
                m.submit_aggregation(
                    EdgeStream.from_arrays(*datasets[i], cfg),
                    HLLDegreeSummary(),
                    name=f"drift-{n_jobs}x-{i}",
                    sink=lambda rec: np.asarray(rec[0]),
                )
            m.wait_all()

    run(1)  # warm the solo update/combine/transform chain
    agg = HLLDegreeSummary()
    fold = agg._superpane_fold_fn(cfg, False)
    for rows in (2, 4, 8, 16):
        states = fold(
            jnp.zeros((rows, win), jnp.int32),
            jnp.zeros((rows, win), jnp.int32),
            None,
            jnp.zeros((rows, win), bool),
        )
        agg._superpane_split_fn(cfg, rows)(states)
    compile_cache.reset_stats()
    run(16)
    run(1)
    stats = compile_cache.stats()
    assert stats["compiles"] == 0, stats
    assert stats["recompiles"] == 0, stats


# ---------------------------------------------------------------------------
# admission: emission-time residue is priced, refusal at the exact byte cap


def test_admission_prices_emission_scratch_at_exact_cap():
    from gelly_streaming_tpu.runtime import JobManager
    from gelly_streaming_tpu.runtime.job import AdmissionError

    cap = 1 << 12
    cfg = StreamConfig(
        vertex_capacity=cap, batch_size=256, ingest_window_edges=256
    )
    agg = CountMinHeavyHitters()
    state = agg.state_nbytes(cfg)
    adm = agg.admission_nbytes(cfg)
    # the top-k's O(C) gathered estimate view dwarfs the persistent grid
    assert adm > state
    assert adm - state >= 4 * cap
    s, d = _rand_edges(256, seed=40, cap=cap)

    def submit(max_bytes):
        with JobManager(
            RuntimeConfig(max_jobs=2, max_state_bytes=max_bytes)
        ) as m:
            m.submit_aggregation(
                EdgeStream.from_arrays(s, d, cfg),
                CountMinHeavyHitters(),
                name=f"adm-{max_bytes}",
                sink=lambda rec: None,
            )
            m.wait_all()

    submit(adm)  # exactly the admission price: fits
    with pytest.raises(AdmissionError):
        submit(adm - 1)  # one byte short: the residue must be charged


# ---------------------------------------------------------------------------
# serving: sketch kinds + knobs in job specs, typed refusals at admission


def test_server_sketch_submit_contract_and_refusals():
    from gelly_streaming_tpu.core.config import ServerConfig
    from gelly_streaming_tpu.runtime import JobManager
    from gelly_streaming_tpu.runtime.client import GellyClient, ServerRefused
    from gelly_streaming_tpu.runtime.server import StreamServer
    from gelly_streaming_tpu.utils import metrics

    cap, w, b = 1 << 12, 1 << 10, 1 << 9
    src, dst = _rand_edges(4 * w, seed=50, cap=cap)
    metrics.reset_sketch_stats()
    with JobManager() as jm, StreamServer(jm, ServerConfig()) as server:
        with GellyClient("127.0.0.1", server.port) as c:
            # malformed knobs and unknown kinds refuse LOUDLY and typed —
            # never a hang, never a silent exact fallback
            with pytest.raises(ServerRefused) as ei:
                c.submit(
                    name="bad-eps",
                    summary="hll_degree",
                    eps=2.0,
                    capacity=cap,
                    window_edges=w,
                    batch=b,
                )
            assert ei.value.code == "bad-spec"
            with pytest.raises(ServerRefused) as ei:
                c.submit(name="bad-kind", summary="bloom", capacity=cap)
            assert ei.value.code == "bad-spec"
            r = c.submit(
                name="hll",
                summary="hll_degree",
                eps=0.05,
                delta=0.05,
                capacity=cap,
                window_edges=w,
                batch=b,
            )
            assert r["error_contract"] == {
                "kind": "hll_degree",
                "eps": 0.05,
                "delta": 0.05,
            }
            # exact queries carry no contract
            r2 = c.submit(
                name="cc",
                query="cc",
                capacity=cap,
                window_edges=w,
                batch=b,
            )
            assert r2["error_contract"] is None
            c.push_edges("hll", src, dst, batch=b, capacity=cap)
            recs = list(c.iter_results("hll", deadline_s=120))
            assert len(recs) == 4
            st = c.call({"verb": "status"})[0]
            row = st["sketch_jobs"]["default/hll"]
            assert row["kind"] == "hll_degree"
            assert row["sketch_eps"] == 0.05
            assert row["sketch_admission_bytes"] >= row["sketch_state_bytes"]
            snap = c.call({"verb": "metrics"})[0]["metrics"]
            assert snap["sketch"]["sketch_jobs_registered"] == 1


def test_make_sketch_validation_and_state_scale():
    with pytest.raises(SketchParamError, match="unknown sketch kind"):
        make_sketch("bloom")
    with pytest.raises(SketchParamError, match="eps"):
        make_sketch("hll_degree", eps=0.0)
    with pytest.raises(SketchParamError, match="delta"):
        make_sketch("sketch_triangles", delta=1.0)
    with pytest.raises(SketchParamError, match="top_k"):
        make_sketch("cm_heavy_hitters", top_k=0)
    small = StreamConfig(vertex_capacity=1 << 10, batch_size=256)
    big = StreamConfig(vertex_capacity=1 << 20, batch_size=256)
    for kind in SKETCH_KINDS:
        agg = make_sketch(kind)
        # the tentpole economics: persistent state is a function of the
        # (eps, delta) contract, NOT of vertex_capacity — KB, not MB
        assert agg.state_nbytes(small) == agg.state_nbytes(big)
        assert agg.state_nbytes(big) < 256 << 10
        assert agg.error_contract()["kind"] == kind
    # the count-min emission residue is the one capacity-coupled price
    cm = make_sketch("cm_heavy_hitters")
    assert cm.admission_nbytes(big) > cm.admission_nbytes(small)
    hll = make_sketch("hll_degree")
    assert hll.admission_nbytes(big) == hll.admission_nbytes(small)

"""GraphSAGE windowed message-passing tests (the new MXU workload)."""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from gelly_streaming_tpu.core.config import StreamConfig
from gelly_streaming_tpu.core.stream import EdgeStream
from gelly_streaming_tpu.core.types import EdgeDirection
from gelly_streaming_tpu.library.graphsage import (
    GraphSAGEWindows,
    SageParams,
    init_params,
    sage_kernel,
)

CFG = StreamConfig(vertex_capacity=16, max_degree=16)


def _numpy_reference(features, params, adj, vertices):
    out = {}
    w_self = np.asarray(params.w_self, np.float32)
    w_nbr = np.asarray(params.w_nbr, np.float32)
    bias = np.asarray(params.bias, np.float32)
    for v in vertices:
        nbrs = adj[v]
        mean = np.mean([features[u] for u in nbrs], axis=0)
        h = features[v] @ w_self + mean @ w_nbr + bias
        out[v] = np.maximum(h, 0.0)
    return out


def test_sage_matches_numpy_reference():
    rng = np.random.default_rng(0)
    features = rng.normal(size=(16, 8)).astype(np.float32)
    params = init_params(jax.random.PRNGKey(0), 8, 4)
    edges = [(1, 2), (1, 3), (2, 3), (3, 4)]
    stream = EdgeStream.from_collection(edges, CFG)
    sage = GraphSAGEWindows(params, features)
    snapshot = stream.slice(1000, EdgeDirection.ALL)
    (keys, emb), = list(sage.run(snapshot))
    adj = {1: [2, 3], 2: [1, 3], 3: [1, 2, 4], 4: [3]}
    want = _numpy_reference(features, params, adj, keys.tolist())
    for i, v in enumerate(keys.tolist()):
        # bf16 matmuls: loose tolerance
        np.testing.assert_allclose(emb[i], want[v], rtol=0.05, atol=0.05)


def test_sage_output_stream():
    features = np.ones((16, 8), np.float32)
    params = SageParams(
        w_self=jnp.eye(8, dtype=jnp.bfloat16),
        w_nbr=jnp.zeros((8, 8), jnp.bfloat16),
        bias=jnp.zeros((8,), jnp.bfloat16),
    )
    stream = EdgeStream.from_collection([(1, 2), (2, 3)], CFG)
    out = GraphSAGEWindows(params, features).output(
        stream.slice(1000, EdgeDirection.ALL)
    )
    recs = dict(out.collect())
    # identity self-projection of all-ones features -> norm sqrt(8)
    assert set(recs) == {1, 2, 3}
    for v, n in recs.items():
        np.testing.assert_allclose(n, np.sqrt(8.0), rtol=1e-2)


def test_sharded_windows_match_single_device():
    """GraphSAGEWindows on the 8-shard mesh (ring feature exchange) must agree
    with the single-device kernel per window (VERDICT r2 missing #6)."""
    import jax

    from gelly_streaming_tpu.core.config import StreamConfig
    from gelly_streaming_tpu.core.stream import EdgeStream
    from gelly_streaming_tpu.core.types import EdgeDirection
    from gelly_streaming_tpu.library.graphsage import (
        GraphSAGEWindows,
        init_params,
    )

    rng = np.random.default_rng(2)
    c, f_in, f_out = 64, 8, 4
    feats = rng.normal(size=(c, f_in)).astype(np.float32)
    params = init_params(jax.random.PRNGKey(0), f_in, f_out)
    edges = list(
        zip(
            rng.integers(0, c, 200).tolist(),
            rng.integers(0, c, 200).tolist(),
        )
    )

    def windows(num_shards):
        cfg = StreamConfig(
            vertex_capacity=c, max_degree=64, batch_size=64, num_shards=num_shards
        )
        stream = EdgeStream.from_collection(edges, cfg, batch_size=64)
        snap = stream.slice(1000, EdgeDirection.OUT)
        return list(GraphSAGEWindows(params, feats).run(snap))

    single = windows(1)
    sharded = windows(8)
    assert len(single) == len(sharded)
    for (k1, e1), (k8, e8) in zip(single, sharded):
        o1, o8 = np.argsort(k1), np.argsort(k8)
        np.testing.assert_array_equal(k1[o1], k8[o8])
        np.testing.assert_allclose(e1[o1], e8[o8], rtol=2e-2, atol=2e-2)


# ---------------------------------------------------------------------------
# training (beyond the reference): unsupervised loss + optax step, single
# device and over the mesh with ring-sharded features


def _train_fixture(seed=0, cap=32, k=16, d=4, f=8):
    rng = np.random.default_rng(seed)
    feats = jnp.asarray(rng.normal(size=(cap, f)).astype(np.float32))
    keys = jnp.asarray(rng.integers(0, cap, k).astype(np.int32))
    nbrs = jnp.asarray(rng.integers(0, cap, (k, d)).astype(np.int32))
    valid = jnp.asarray(rng.random((k, d)) < 0.7)
    return feats, keys, nbrs, valid


def test_sage_training_reduces_loss():
    from gelly_streaming_tpu.library import graphsage as gs

    feats, keys, nbrs, valid = _train_fixture()
    tx = optax.adam(3e-2)
    state = gs.sage_init_train(jax.random.key(0), feats.shape[1], 8, tx)
    pos, has, neg = gs.sample_pairs(jax.random.key(1), nbrs, valid, feats.shape[0])
    step = jax.jit(lambda st: gs.sage_train_step(
        tx, st, feats, keys, nbrs, valid, pos, has, neg))
    first = None
    for i in range(60):
        state, loss = step(state)
        if first is None:
            first = float(loss)
    assert float(loss) < 0.5 * first, (first, float(loss))
    for leaf in jax.tree.leaves(state.params):
        assert bool(jnp.isfinite(leaf).all())


def test_sage_mesh_loss_and_grad_match_single_device():
    from gelly_streaming_tpu.library import graphsage as gs
    from gelly_streaming_tpu.parallel.ring import shard_features

    s_n = 8
    feats, keys, nbrs, valid = _train_fixture(cap=32, k=16)
    tx = optax.adam(1e-2)
    state = gs.sage_init_train(jax.random.key(0), feats.shape[1], 8, tx)
    pos, has, neg = gs.sample_pairs(jax.random.key(1), nbrs, valid, feats.shape[0])

    single = gs.sage_loss(state.params, feats, keys, nbrs, valid, pos, has, neg)
    g_single = jax.grad(gs.sage_loss)(
        state.params, feats, keys, nbrs, valid, pos, has, neg
    )

    blocks = jnp.asarray(shard_features(np.asarray(feats), s_n))
    shard = lambda a: a.reshape((s_n, -1) + a.shape[1:])
    mesh_loss = gs.sage_loss_mesh(
        state.params, blocks, shard(keys), shard(nbrs), shard(valid),
        shard(pos), shard(has), shard(neg), s_n,
    )
    np.testing.assert_allclose(float(mesh_loss), float(single), rtol=2e-2)

    g_mesh = jax.grad(gs.sage_loss_mesh)(
        state.params, blocks, shard(keys), shard(nbrs), shard(valid),
        shard(pos), shard(has), shard(neg), s_n,
    )
    for a, b in zip(jax.tree.leaves(g_single), jax.tree.leaves(g_mesh)):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=5e-2, atol=5e-3
        )


def test_sage_mesh_training_reduces_loss():
    from gelly_streaming_tpu.library import graphsage as gs
    from gelly_streaming_tpu.parallel.ring import shard_features

    s_n = 8
    feats, keys, nbrs, valid = _train_fixture(seed=3, cap=32, k=16)
    tx = optax.adam(3e-2)
    state = gs.sage_init_train(jax.random.key(0), feats.shape[1], 8, tx)
    pos, has, neg = gs.sample_pairs(jax.random.key(1), nbrs, valid, feats.shape[0])
    blocks = jnp.asarray(shard_features(np.asarray(feats), s_n))
    shard = lambda a: a.reshape((s_n, -1) + a.shape[1:])
    args = (blocks, shard(keys), shard(nbrs), shard(valid),
            shard(pos), shard(has), shard(neg))
    step = jax.jit(lambda st: gs.sage_train_step_mesh(tx, st, *args, s_n))
    first = None
    for _ in range(40):
        state, loss = step(state)
        if first is None:
            first = float(loss)
    assert float(loss) < 0.6 * first, (first, float(loss))


# ---------------------------------------------------------------------------
# stacked (multi-layer) windows


def _np_sage_layer(p, feats, adj):
    """Host reference of one sage layer over a dict vertex -> neighbor list."""
    w_s = np.asarray(p.w_self, np.float32)
    w_n = np.asarray(p.w_nbr, np.float32)
    b = np.asarray(p.bias, np.float32)
    out = {}
    for v, nbrs in adj.items():
        mean = np.mean([feats[u] for u in nbrs], axis=0)
        out[v] = np.maximum(feats[v] @ w_s + mean @ w_n + b, 0.0)
    return out


def test_two_layer_windows_match_host_reference():
    from gelly_streaming_tpu.library.graphsage import GraphSAGEWindows, init_params

    cap, f = 16, 8
    edges = [(1, 2), (2, 3), (3, 4), (4, 1)]
    adj = {1: [2, 4], 2: [1, 3], 3: [2, 4], 4: [3, 1]}
    rng = np.random.default_rng(0)
    feats = rng.normal(size=(cap, f)).astype(np.float32)
    p1 = init_params(jax.random.key(1), f, f)
    p2 = init_params(jax.random.key(2), f, f)

    cfg = StreamConfig(vertex_capacity=cap, max_degree=8, batch_size=4)
    stream = EdgeStream.from_collection(edges, cfg)
    model = GraphSAGEWindows([p1, p2], feats)
    windows = list(model.run(stream.slice(1000, EdgeDirection.ALL)))
    assert len(windows) == 1
    keys, emb = windows[0]

    h1 = _np_sage_layer(p1, {v: feats[v] for v in adj}, adj)
    h1_full = {v: h1.get(v, np.zeros(f, np.float32)) for v in adj}
    h2 = _np_sage_layer(p2, h1_full, adj)
    for v, e in zip(keys.tolist(), emb):
        np.testing.assert_allclose(e, h2[v], rtol=5e-2, atol=5e-2)


def test_two_layer_sharded_matches_single_device():
    from gelly_streaming_tpu.library.graphsage import GraphSAGEWindows, init_params

    cap, f = 16, 8
    rng = np.random.default_rng(1)
    edges = [
        (int(rng.integers(0, cap)), int(rng.integers(0, cap))) for _ in range(24)
    ]
    feats = rng.normal(size=(cap, f)).astype(np.float32)
    layers = [init_params(jax.random.key(3), f, f), init_params(jax.random.key(4), f, f)]

    def run(num_shards):
        cfg = StreamConfig(
            vertex_capacity=cap, max_degree=32, batch_size=8, num_shards=num_shards
        )
        stream = EdgeStream.from_collection(edges, cfg, batch_size=8)
        model = GraphSAGEWindows(layers, feats)
        out = {}
        for keys, emb in model.run(stream.slice(1000, EdgeDirection.ALL)):
            for v, e in zip(keys.tolist(), emb):
                out[v] = e
        return out

    single, sharded = run(1), run(8)
    assert set(single) == set(sharded)
    for v in single:
        np.testing.assert_allclose(sharded[v], single[v], rtol=5e-2, atol=5e-2)


def test_stacked_layers_validation():
    from gelly_streaming_tpu.library.graphsage import GraphSAGEWindows, init_params

    feats = np.zeros((8, 4), np.float32)
    with pytest.raises(TypeError, match="SageParams"):
        GraphSAGEWindows([], feats)
    with pytest.raises(TypeError, match="SageParams"):
        GraphSAGEWindows([("not", "params", "!")], feats)
    p = init_params(jax.random.key(0), 4, 4)
    cfg = StreamConfig(vertex_capacity=8, max_degree=8, batch_size=4)
    stream = EdgeStream.from_collection([(1, 2), (2, 3)], cfg)
    with pytest.raises(ValueError, match="ALL"):
        list(
            GraphSAGEWindows([p, p], feats).run(
                stream.slice(1000, EdgeDirection.OUT)
            )
        )


def test_stacked_sharded_fires_late_sink_once():
    """The stacked mesh path's second bucket pass must not re-deliver late
    records to on_late (it rebuilds windows on a sink-less clone)."""
    from gelly_streaming_tpu.library.graphsage import GraphSAGEWindows, init_params

    cap, f = 16, 4
    feats = np.zeros((cap, f), np.float32)
    layers = [init_params(jax.random.key(0), f, f)] * 2
    edges = [
        (1, 2, 0.0, 100),
        (3, 4, 0.0, 1500),
        (1, 5, 0.0, 100),  # late beyond bound=0
        (2, 3, 0.0, 2600),
    ]
    cfg = StreamConfig(
        vertex_capacity=cap, max_degree=8, batch_size=1, num_shards=8
    )
    stream = EdgeStream.from_collection(edges, cfg, batch_size=1, with_time=True)
    lates = []
    stream.on_late(lambda s, d, v, t: lates.append(len(s)))
    list(
        GraphSAGEWindows(layers, feats).run(
            stream.slice(1000, EdgeDirection.ALL)
        )
    )
    assert lates == [1]  # delivered exactly once, not once per pass


def test_stacked_sharded_refuses_wall_clock_panes():
    from gelly_streaming_tpu.library.graphsage import GraphSAGEWindows, init_params

    p = init_params(jax.random.key(0), 4, 4)
    feats = np.zeros((16, 4), np.float32)
    cfg = StreamConfig(
        vertex_capacity=16, max_degree=8, batch_size=2, num_shards=8,
        ingest_window_ms=50,
    )
    stream = EdgeStream.from_collection([(1, 2), (2, 3)], cfg)
    with pytest.raises(ValueError, match="replay-deterministic"):
        list(
            GraphSAGEWindows([p, p], feats).run(
                stream.slice(1000, EdgeDirection.ALL)
            )
        )

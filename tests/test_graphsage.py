"""GraphSAGE windowed message-passing tests (the new MXU workload)."""

import jax
import jax.numpy as jnp
import numpy as np
import optax

from gelly_streaming_tpu.core.config import StreamConfig
from gelly_streaming_tpu.core.stream import EdgeStream
from gelly_streaming_tpu.core.types import EdgeDirection
from gelly_streaming_tpu.library.graphsage import (
    GraphSAGEWindows,
    SageParams,
    init_params,
    sage_kernel,
)

CFG = StreamConfig(vertex_capacity=16, max_degree=16)


def _numpy_reference(features, params, adj, vertices):
    out = {}
    w_self = np.asarray(params.w_self, np.float32)
    w_nbr = np.asarray(params.w_nbr, np.float32)
    bias = np.asarray(params.bias, np.float32)
    for v in vertices:
        nbrs = adj[v]
        mean = np.mean([features[u] for u in nbrs], axis=0)
        h = features[v] @ w_self + mean @ w_nbr + bias
        out[v] = np.maximum(h, 0.0)
    return out


def test_sage_matches_numpy_reference():
    rng = np.random.default_rng(0)
    features = rng.normal(size=(16, 8)).astype(np.float32)
    params = init_params(jax.random.PRNGKey(0), 8, 4)
    edges = [(1, 2), (1, 3), (2, 3), (3, 4)]
    stream = EdgeStream.from_collection(edges, CFG)
    sage = GraphSAGEWindows(params, features)
    snapshot = stream.slice(1000, EdgeDirection.ALL)
    (keys, emb), = list(sage.run(snapshot))
    adj = {1: [2, 3], 2: [1, 3], 3: [1, 2, 4], 4: [3]}
    want = _numpy_reference(features, params, adj, keys.tolist())
    for i, v in enumerate(keys.tolist()):
        # bf16 matmuls: loose tolerance
        np.testing.assert_allclose(emb[i], want[v], rtol=0.05, atol=0.05)


def test_sage_output_stream():
    features = np.ones((16, 8), np.float32)
    params = SageParams(
        w_self=jnp.eye(8, dtype=jnp.bfloat16),
        w_nbr=jnp.zeros((8, 8), jnp.bfloat16),
        bias=jnp.zeros((8,), jnp.bfloat16),
    )
    stream = EdgeStream.from_collection([(1, 2), (2, 3)], CFG)
    out = GraphSAGEWindows(params, features).output(
        stream.slice(1000, EdgeDirection.ALL)
    )
    recs = dict(out.collect())
    # identity self-projection of all-ones features -> norm sqrt(8)
    assert set(recs) == {1, 2, 3}
    for v, n in recs.items():
        np.testing.assert_allclose(n, np.sqrt(8.0), rtol=1e-2)


def test_sharded_windows_match_single_device():
    """GraphSAGEWindows on the 8-shard mesh (ring feature exchange) must agree
    with the single-device kernel per window (VERDICT r2 missing #6)."""
    import jax

    from gelly_streaming_tpu.core.config import StreamConfig
    from gelly_streaming_tpu.core.stream import EdgeStream
    from gelly_streaming_tpu.core.types import EdgeDirection
    from gelly_streaming_tpu.library.graphsage import (
        GraphSAGEWindows,
        init_params,
    )

    rng = np.random.default_rng(2)
    c, f_in, f_out = 64, 8, 4
    feats = rng.normal(size=(c, f_in)).astype(np.float32)
    params = init_params(jax.random.PRNGKey(0), f_in, f_out)
    edges = list(
        zip(
            rng.integers(0, c, 200).tolist(),
            rng.integers(0, c, 200).tolist(),
        )
    )

    def windows(num_shards):
        cfg = StreamConfig(
            vertex_capacity=c, max_degree=64, batch_size=64, num_shards=num_shards
        )
        stream = EdgeStream.from_collection(edges, cfg, batch_size=64)
        snap = stream.slice(1000, EdgeDirection.OUT)
        return list(GraphSAGEWindows(params, feats).run(snap))

    single = windows(1)
    sharded = windows(8)
    assert len(single) == len(sharded)
    for (k1, e1), (k8, e8) in zip(single, sharded):
        o1, o8 = np.argsort(k1), np.argsort(k8)
        np.testing.assert_array_equal(k1[o1], k8[o8])
        np.testing.assert_allclose(e1[o1], e8[o8], rtol=2e-2, atol=2e-2)


# ---------------------------------------------------------------------------
# training (beyond the reference): unsupervised loss + optax step, single
# device and over the mesh with ring-sharded features


def _train_fixture(seed=0, cap=32, k=16, d=4, f=8):
    rng = np.random.default_rng(seed)
    feats = jnp.asarray(rng.normal(size=(cap, f)).astype(np.float32))
    keys = jnp.asarray(rng.integers(0, cap, k).astype(np.int32))
    nbrs = jnp.asarray(rng.integers(0, cap, (k, d)).astype(np.int32))
    valid = jnp.asarray(rng.random((k, d)) < 0.7)
    return feats, keys, nbrs, valid


def test_sage_training_reduces_loss():
    from gelly_streaming_tpu.library import graphsage as gs

    feats, keys, nbrs, valid = _train_fixture()
    tx = optax.adam(3e-2)
    state = gs.sage_init_train(jax.random.key(0), feats.shape[1], 8, tx)
    pos, has, neg = gs.sample_pairs(jax.random.key(1), nbrs, valid, feats.shape[0])
    step = jax.jit(lambda st: gs.sage_train_step(
        tx, st, feats, keys, nbrs, valid, pos, has, neg))
    first = None
    for i in range(60):
        state, loss = step(state)
        if first is None:
            first = float(loss)
    assert float(loss) < 0.5 * first, (first, float(loss))
    for leaf in jax.tree.leaves(state.params):
        assert bool(jnp.isfinite(leaf).all())


def test_sage_mesh_loss_and_grad_match_single_device():
    from gelly_streaming_tpu.library import graphsage as gs
    from gelly_streaming_tpu.parallel.ring import shard_features

    s_n = 8
    feats, keys, nbrs, valid = _train_fixture(cap=32, k=16)
    tx = optax.adam(1e-2)
    state = gs.sage_init_train(jax.random.key(0), feats.shape[1], 8, tx)
    pos, has, neg = gs.sample_pairs(jax.random.key(1), nbrs, valid, feats.shape[0])

    single = gs.sage_loss(state.params, feats, keys, nbrs, valid, pos, has, neg)
    g_single = jax.grad(gs.sage_loss)(
        state.params, feats, keys, nbrs, valid, pos, has, neg
    )

    blocks = jnp.asarray(shard_features(np.asarray(feats), s_n))
    shard = lambda a: a.reshape((s_n, -1) + a.shape[1:])
    mesh_loss = gs.sage_loss_mesh(
        state.params, blocks, shard(keys), shard(nbrs), shard(valid),
        shard(pos), shard(has), shard(neg), s_n,
    )
    np.testing.assert_allclose(float(mesh_loss), float(single), rtol=2e-2)

    g_mesh = jax.grad(gs.sage_loss_mesh)(
        state.params, blocks, shard(keys), shard(nbrs), shard(valid),
        shard(pos), shard(has), shard(neg), s_n,
    )
    for a, b in zip(jax.tree.leaves(g_single), jax.tree.leaves(g_mesh)):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=5e-2, atol=5e-3
        )


def test_sage_mesh_training_reduces_loss():
    from gelly_streaming_tpu.library import graphsage as gs
    from gelly_streaming_tpu.parallel.ring import shard_features

    s_n = 8
    feats, keys, nbrs, valid = _train_fixture(seed=3, cap=32, k=16)
    tx = optax.adam(3e-2)
    state = gs.sage_init_train(jax.random.key(0), feats.shape[1], 8, tx)
    pos, has, neg = gs.sample_pairs(jax.random.key(1), nbrs, valid, feats.shape[0])
    blocks = jnp.asarray(shard_features(np.asarray(feats), s_n))
    shard = lambda a: a.reshape((s_n, -1) + a.shape[1:])
    args = (blocks, shard(keys), shard(nbrs), shard(valid),
            shard(pos), shard(has), shard(neg))
    step = jax.jit(lambda st: gs.sage_train_step_mesh(tx, st, *args, s_n))
    first = None
    for _ in range(40):
        state, loss = step(state)
        if first is None:
            first = float(loss)
    assert float(loss) < 0.6 * first, (first, float(loss))

"""Edge-routing tests: host keyBy analog and the device all_to_all re-key."""

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec as P

from gelly_streaming_tpu.parallel.mesh import make_mesh, shard_map
from gelly_streaming_tpu.parallel.routing import device_route, host_route


def test_host_route_partitions_by_owner():
    src = np.array([0, 1, 2, 3, 8, 9, 17], np.int32)
    dst = np.array([5, 6, 7, 8, 9, 10, 11], np.int32)
    routed = host_route(src, dst, num_shards=8)
    # every valid edge lands on owner(src) = src % 8
    for shard in range(8):
        m = routed.mask[shard]
        assert np.all(routed.src[shard][m] % 8 == shard)
    # nothing lost
    got = sorted(
        (int(s), int(d))
        for s_row, d_row, m_row in zip(routed.src, routed.dst, routed.mask)
        for s, d, m in zip(s_row, d_row, m_row)
        if m
    )
    assert got == sorted(zip(src.tolist(), dst.tolist()))


def test_device_route_matches_host_route():
    rng = np.random.default_rng(11)
    n_shards, b = 8, 32
    src = rng.integers(0, 64, (n_shards, b)).astype(np.int32)
    dst = rng.integers(0, 64, (n_shards, b)).astype(np.int32)
    mask = rng.random((n_shards, b)) < 0.9

    mesh = make_mesh(n_shards)
    cap = b  # worst case: all of a shard's edges go to one owner

    def routed_body(s, d, m):
        r_s, r_d, r_m, dropped = device_route(
            s.reshape(-1), d.reshape(-1), m.reshape(-1), n_shards, cap
        )
        return r_s, r_d, r_m, dropped.reshape(1)

    route = jax.jit(
        shard_map(
            routed_body,
            mesh=mesh,
            in_specs=(P("shards"), P("shards"), P("shards")),
            out_specs=(P("shards"), P("shards"), P("shards"), P("shards")),
        )
    )
    r_src, r_dst, r_mask, dropped = route(
        jnp.asarray(src), jnp.asarray(dst), jnp.asarray(mask)
    )
    assert int(np.asarray(dropped).sum()) == 0
    r_src, r_dst, r_mask = map(np.asarray, (r_src, r_dst, r_mask))
    # received shape: [n_shards * cap] per shard -> [n_shards, n_shards * cap]
    r_src = r_src.reshape(n_shards, -1)
    r_dst = r_dst.reshape(n_shards, -1)
    r_mask = r_mask.reshape(n_shards, -1)

    # every shard holds exactly the valid edges it owns
    for shard in range(n_shards):
        m = r_mask[shard]
        assert np.all(r_src[shard][m] % n_shards == shard)
    got = sorted(
        (int(s), int(d))
        for srow, drow, mrow in zip(r_src, r_dst, r_mask)
        for s, d, m in zip(srow, drow, mrow)
        if m
    )
    want = sorted(
        (int(s), int(d))
        for srow, drow, mrow in zip(src, dst, mask)
        for s, d, m in zip(srow, drow, mrow)
        if m
    )
    assert got == want


def test_device_route_counts_drops_and_salting_avoids_them():
    """Power-law skew (VERDICT r1 item 5): one hub key owns most edges.  Exact
    routing under a tight per-(sender,receiver) cap must COUNT its drops (never
    silent); salted routing spreads the hub across shards, drops nothing, and
    a psum second stage recovers exact per-key counts."""
    from gelly_streaming_tpu.ops import segments
    from gelly_streaming_tpu.parallel.mesh import SHARD_AXIS
    from gelly_streaming_tpu.parallel.routing import device_route_salted

    n_shards, b = 8, 64
    n_keys = 64
    rng = np.random.default_rng(5)
    # hub vertex 7 is ~80% of all routing keys
    src = np.where(
        rng.random((n_shards, b)) < 0.8, 7, rng.integers(0, n_keys, (n_shards, b))
    ).astype(np.int32)
    dst = rng.integers(0, n_keys, (n_shards, b)).astype(np.int32)
    mask = np.ones((n_shards, b), bool)
    # 2x the uniform mean: a salted (near-uniform) spread fits with headroom,
    # a hub bucket (~0.8*b edges to ONE receiver) does not
    cap = 2 * b // n_shards

    mesh = make_mesh(n_shards)

    def make(route_fn, with_counts=False):
        def body(s, d, m):
            r_s, r_d, r_m, dropped = route_fn(
                s.reshape(-1), d.reshape(-1), m.reshape(-1), n_shards, cap
            )
            if not with_counts:
                return r_s, r_m, dropped.reshape(1)
            partial = segments.segment_sum(
                jnp.where(r_m, 1, 0), r_s, n_keys, r_m
            )
            counts = jax.lax.psum(partial, SHARD_AXIS)  # second-stage combine
            return counts, r_m, dropped.reshape(1)

        specs_out = (P(), P("shards"), P("shards")) if with_counts else (
            P("shards"), P("shards"), P("shards")
        )
        return jax.jit(
            shard_map(
                body,
                mesh=mesh,
                in_specs=(P("shards"), P("shards"), P("shards")),
                out_specs=specs_out,
            )
        )

    # exact routing: the hub overflows the tight cap -> counted drops
    _, _, dropped = make(device_route)(
        jnp.asarray(src), jnp.asarray(dst), jnp.asarray(mask)
    )
    assert int(np.asarray(dropped).sum()) > 0

    # salted routing: zero drops, and per-key counts are exact after psum
    counts, r_mask, dropped_s = make(device_route_salted, with_counts=True)(
        jnp.asarray(src), jnp.asarray(dst), jnp.asarray(mask)
    )
    assert int(np.asarray(dropped_s).sum()) == 0
    expected = np.bincount(src.reshape(-1), minlength=n_keys)
    assert np.array_equal(np.asarray(counts)[:n_keys], expected)


def test_native_router_matches_numpy(monkeypatch):
    """The single-pass native scatter must produce the numpy path's buckets
    bit-for-bit (stable arrival order per shard)."""
    from gelly_streaming_tpu.parallel import routing
    from gelly_streaming_tpu.utils.native import load_ingest_lib

    lib = load_ingest_lib()
    if lib is None or not hasattr(lib, "route_edges"):
        pytest.skip("native route_edges unavailable")
    rng = np.random.default_rng(23)
    src = rng.integers(0, 1000, 5000).astype(np.int32)
    dst = rng.integers(0, 1000, 5000).astype(np.int32)
    # negative keys (ids wrapped past 2^31) must route with floored modulo,
    # same as numpy '%' — exercised on a non-power-of-two shard count too
    src[:4] = [-5, -1, -1000, 3]
    for num_shards, key in ((8, "src"), (8, "dst"), (3, "src")):
        native = routing.host_route(src, dst, num_shards, key=key)
        import gelly_streaming_tpu.utils.native as native_mod

        monkeypatch.setattr(native_mod, "load_ingest_lib", lambda: None)
        numpy_r = routing.host_route(src, dst, num_shards, key=key)
        monkeypatch.undo()
        np.testing.assert_array_equal(native.src, numpy_r.src)
        np.testing.assert_array_equal(native.dst, numpy_r.dst)
        np.testing.assert_array_equal(native.mask, numpy_r.mask)


def test_salted_routing_survives_zipf_skew():
    """SURVEY §7 "skewed keys" / VERDICT r3 item 7: on a zipf-keyed batch a
    fixed per-(sender,receiver) capacity makes plain device_route overflow
    (counted drops), while device_route_salted spreads each hot key's
    occurrences across shards — zero drops and bounded per-shard receive
    imbalance on the same batch.  Drives measure_routing directly (one
    harness, shared with the measurements CLI)."""
    import argparse

    from gelly_streaming_tpu.examples.measurements import measure_routing

    out = measure_routing(
        argparse.Namespace(
            shards=8,
            batch=256,
            capacity=64,  # mesh capacity 8*8*64 = 4096 >= 2048: volume fits
            vertices=1 << 12,
            alpha=1.3,
            seed=0,
        )
    )
    # the zipf head (key 0 dominates) overflows the plain router's fixed cap
    assert out["plain_dropped"] > 0
    # salting spreads the head: nothing drops, receive volume stays balanced
    assert out["salted_dropped"] == 0
    assert out["salted_recv_imbalance"] <= 1.5, out
    assert out["plain_recv_imbalance"] > out["salted_recv_imbalance"]


def test_device_route_fuzz_vs_host_route_oracle():
    """device_route must deliver exactly host_route's multiset per owner —
    fuzzed over skewed (every edge one shard), empty-shard, and valued-pytree
    distributions (ISSUE 4 satellite)."""
    import jax

    from gelly_streaming_tpu.parallel.routing import pow2_bucket

    n_shards, b = 8, 24
    mesh = make_mesh(n_shards)

    def run_device(src, dst, mask, cap, val=None):
        def body(s, d, m, *v):
            routed = device_route(
                s.reshape(-1),
                d.reshape(-1),
                m.reshape(-1),
                n_shards,
                cap,
                val=jax.tree.map(lambda a: a.reshape((-1,) + a.shape[2:]), v[0])
                if v
                else None,
            )
            out = (routed.src, routed.dst, routed.mask, routed.dropped.reshape(1))
            if v:
                out = out + (routed.val,)
            return out

        n_out = 5 if val is not None else 4
        specs_in = (P("shards"),) * 3 + ((P("shards"),) if val is not None else ())
        route = jax.jit(
            shard_map(
                body,
                mesh=mesh,
                in_specs=specs_in,
                out_specs=(P("shards"),) * n_out,
            )
        )
        args = [jnp.asarray(src), jnp.asarray(dst), jnp.asarray(mask)]
        if val is not None:
            args.append(jax.tree.map(jnp.asarray, val))
        out = route(*args)
        cap_b = pow2_bucket(cap)
        rs = np.asarray(out[0]).reshape(n_shards, -1)
        rd = np.asarray(out[1]).reshape(n_shards, -1)
        rm = np.asarray(out[2]).reshape(n_shards, -1)
        dropped = int(np.asarray(out[3]).sum())
        rv = None
        if val is not None:
            rv = jax.tree.map(
                lambda a: np.asarray(a).reshape((n_shards, n_shards * cap_b) + a.shape[1:]),
                out[4],
            )
        return rs, rd, rm, dropped, rv

    rng = np.random.default_rng(77)
    cases = []
    # uniform
    cases.append((rng.integers(0, 64, (n_shards, b)), rng.integers(0, 64, (n_shards, b)), rng.random((n_shards, b)) < 0.9, None))
    # skewed: EVERY edge keyed to shard 3
    cases.append((rng.integers(0, 8, (n_shards, b)) * 8 + 3, rng.integers(0, 64, (n_shards, b)), np.ones((n_shards, b), bool), None))
    # empty shards: only shard 0's rows valid, keyed to two owners
    m = np.zeros((n_shards, b), bool)
    m[0] = True
    cases.append((rng.integers(0, 2, (n_shards, b)) * 8 + rng.integers(0, 2, (n_shards, b)), rng.integers(0, 64, (n_shards, b)), m, None))
    # valued pytree payload
    val = {
        "w": rng.normal(size=(n_shards, b)).astype(np.float32),
        "tag": rng.integers(0, 100, (n_shards, b, 2)).astype(np.int32),
    }
    cases.append((rng.integers(0, 64, (n_shards, b)), rng.integers(0, 64, (n_shards, b)), rng.random((n_shards, b)) < 0.8, val))

    for src, dst, mask, v in cases:
        src = src.astype(np.int32)
        dst = dst.astype(np.int32)
        cap = n_shards * b  # lossless: a shard may send its whole batch to one owner
        rs, rd, rm, dropped, rv = run_device(src, dst, mask, cap, v)
        assert dropped == 0
        flat_sel = mask.reshape(-1)
        oracle = host_route(
            src.reshape(-1)[flat_sel],
            dst.reshape(-1)[flat_sel],
            n_shards,
            val=None
            if v is None
            else jax.tree.map(lambda a: a.reshape((-1,) + a.shape[2:])[flat_sel], v),
        )
        for shard in range(n_shards):
            got = sorted(
                (int(s), int(d))
                for s, d, ok in zip(rs[shard], rd[shard], rm[shard])
                if ok
            )
            want = sorted(
                (int(s), int(d))
                for s, d, ok in zip(
                    oracle.src[shard], oracle.dst[shard], oracle.mask[shard]
                )
                if ok
            )
            assert got == want, f"shard {shard} multiset mismatch"
            if v is not None:
                got_v = sorted(
                    (int(s), float(w), tuple(int(x) for x in tg))
                    for s, w, tg, ok in zip(
                        rs[shard],
                        rv["w"][shard],
                        rv["tag"][shard],
                        rm[shard],
                    )
                    if ok
                )
                want_v = sorted(
                    (int(s), float(w), tuple(int(x) for x in tg))
                    for s, w, tg, ok in zip(
                        oracle.src[shard],
                        oracle.val["w"][shard],
                        oracle.val["tag"][shard],
                        oracle.mask[shard],
                    )
                    if ok
                )
                assert got_v == want_v, f"shard {shard} payload mismatch"


def test_host_route_auto_capacity_is_pow2_bucketed():
    from gelly_streaming_tpu.parallel.routing import pow2_bucket

    rng = np.random.default_rng(3)
    for n in (7, 33, 130):
        src = rng.integers(0, 64, n).astype(np.int32)
        dst = rng.integers(0, 64, n).astype(np.int32)
        routed = host_route(src, dst, 8)
        cap = routed.src.shape[1]
        assert cap == pow2_bucket(cap), cap  # a power of two
    # explicit capacities are honored as given (no silent reshaping)
    routed = host_route(src, dst, 8, capacity=50)
    assert routed.src.shape[1] == 50


def test_pack_slab_deltas_matches_numpy_oracle():
    """The delta-buffer compaction: changed rows land per owner in block-row
    order, padding carries the fill, occupancy/spill/sent are exact."""
    import jax

    from gelly_streaming_tpu.parallel.routing import DELTA_PAD, pack_slab_deltas

    rng = np.random.default_rng(5)
    C, S_, cap = 64, 8, 4
    changed = rng.random(C) < 0.4
    values = rng.integers(0, 1000, C).astype(np.int32)
    rows, vals, sent, occ, spilled = jax.jit(
        lambda c, v: pack_slab_deltas(c, v, S_, cap, fill=-7)
    )(jnp.asarray(changed), jnp.asarray(values))
    rows, vals, sent = np.asarray(rows), np.asarray(vals), np.asarray(sent)
    demand = np.zeros(S_, np.int64)
    for owner in range(S_):
        ids = [g for g in range(C) if g % S_ == owner and changed[g]]
        demand[owner] = len(ids)
        kept = ids[:cap]
        got = [(int(r), int(x)) for r, x in zip(rows[owner], vals[owner]) if r != DELTA_PAD]
        assert got == [(g // S_, int(values[g])) for g in kept]
        # padding slots carry the fill value
        assert all(int(x) == -7 for r, x in zip(rows[owner], vals[owner]) if r == DELTA_PAD)
        for g in ids:
            assert bool(sent[g]) == (g in kept)
    assert int(occ) == demand.max()
    assert int(spilled) == int(np.maximum(demand - cap, 0).sum())
    assert not sent[~changed].any()


def test_slab_exchange_and_gather_blocks_roundtrip():
    """slab_exchange routes owner slabs; gather_blocks reassembles the
    modulo-interleaved full view — together they invert block sharding."""
    import jax

    from gelly_streaming_tpu.parallel.routing import gather_blocks, slab_exchange

    S_ = 8
    C = 64
    mesh = make_mesh(S_)
    full = np.arange(S_ * C, dtype=np.int32).reshape(S_, C)  # per-shard [C] views

    def body(v, blk):
        recv = slab_exchange(v[0], S_, "shards")
        # keep MY slab of my own view: what shard me sent to me
        me = jax.lax.axis_index("shards")
        own = recv[me]
        return recv[None], gather_blocks(blk[0], S_, "shards")[None], own[None]

    blocks = np.arange(C, dtype=np.int32).reshape(-1, S_).T.copy()  # [S, C/S]
    f = jax.jit(
        shard_map(
            body,
            mesh=mesh,
            in_specs=(P("shards"), P("shards")),
            out_specs=(P("shards"), P("shards"), P("shards")),
        )
    )
    recv, gathered, own = f(jnp.asarray(full), jnp.asarray(blocks))
    recv = np.asarray(recv).reshape(S_, S_, C // S_)
    # shard o's received slab from sender s == sender s's values for o's rows
    for o in range(S_):
        for s in range(S_):
            assert np.array_equal(recv[o, s], full[s].reshape(-1, S_).T[o])
    # gather_blocks reassembles v = s + S*i from blocks[s, i]
    gathered = np.asarray(gathered).reshape(S_, C)
    for o in range(S_):
        assert np.array_equal(gathered[o], np.arange(C, dtype=np.int32))


def test_routing_measurement_cli():
    """The measurements CLI surfaces the same line end-to-end via argv."""
    import contextlib
    import io
    import json

    from gelly_streaming_tpu.examples.measurements import main

    buf = io.StringIO()
    with contextlib.redirect_stdout(buf):
        main(["routing", "--shards", "8", "--batch", "256", "--capacity", "64"])
    out = json.loads(buf.getvalue().strip().splitlines()[-1])
    assert out["plain_dropped"] > 0
    assert out["salted_dropped"] == 0

"""Edge-routing tests: host keyBy analog and the device all_to_all re-key."""

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec as P

from gelly_streaming_tpu.parallel.mesh import make_mesh, shard_map
from gelly_streaming_tpu.parallel.routing import device_route, host_route


def test_host_route_partitions_by_owner():
    src = np.array([0, 1, 2, 3, 8, 9, 17], np.int32)
    dst = np.array([5, 6, 7, 8, 9, 10, 11], np.int32)
    routed = host_route(src, dst, num_shards=8)
    # every valid edge lands on owner(src) = src % 8
    for shard in range(8):
        m = routed.mask[shard]
        assert np.all(routed.src[shard][m] % 8 == shard)
    # nothing lost
    got = sorted(
        (int(s), int(d))
        for s_row, d_row, m_row in zip(routed.src, routed.dst, routed.mask)
        for s, d, m in zip(s_row, d_row, m_row)
        if m
    )
    assert got == sorted(zip(src.tolist(), dst.tolist()))


def test_device_route_matches_host_route():
    rng = np.random.default_rng(11)
    n_shards, b = 8, 32
    src = rng.integers(0, 64, (n_shards, b)).astype(np.int32)
    dst = rng.integers(0, 64, (n_shards, b)).astype(np.int32)
    mask = rng.random((n_shards, b)) < 0.9

    mesh = make_mesh(n_shards)
    cap = b  # worst case: all of a shard's edges go to one owner

    route = jax.jit(
        shard_map(
            lambda s, d, m: device_route(
                s.reshape(-1), d.reshape(-1), m.reshape(-1), n_shards, cap
            ),
            mesh=mesh,
            in_specs=(P("shards"), P("shards"), P("shards")),
            out_specs=(P("shards"), P("shards"), P("shards")),
        )
    )
    r_src, r_dst, r_mask = route(
        jnp.asarray(src), jnp.asarray(dst), jnp.asarray(mask)
    )
    r_src, r_dst, r_mask = map(np.asarray, (r_src, r_dst, r_mask))
    # received shape: [n_shards * cap] per shard -> [n_shards, n_shards * cap]
    r_src = r_src.reshape(n_shards, -1)
    r_dst = r_dst.reshape(n_shards, -1)
    r_mask = r_mask.reshape(n_shards, -1)

    # every shard holds exactly the valid edges it owns
    for shard in range(n_shards):
        m = r_mask[shard]
        assert np.all(r_src[shard][m] % n_shards == shard)
    got = sorted(
        (int(s), int(d))
        for srow, drow, mrow in zip(r_src, r_dst, r_mask)
        for s, d, m in zip(srow, drow, mrow)
        if m
    )
    want = sorted(
        (int(s), int(d))
        for srow, drow, mrow in zip(src, dst, mask)
        for s, d, m in zip(srow, drow, mrow)
        if m
    )
    assert got == want

"""Bipartiteness check tests mirroring BipartitenessCheckTest.java goldens."""

from gelly_streaming_tpu.core.config import StreamConfig
from gelly_streaming_tpu.core.stream import EdgeStream
from gelly_streaming_tpu.library.bipartiteness import BipartitenessCheck

CFG = StreamConfig(vertex_capacity=16, max_degree=16)

BIPARTITE_EDGES = [
    (1, 2),
    (1, 3),
    (1, 4),
    (4, 5),
    (4, 7),
    (4, 9),
]  # BipartitenessCheckTest.java:70-79

NON_BIPARTITE_EDGES = [
    (1, 2),
    (2, 3),
    (3, 1),
    (4, 5),
    (5, 7),
    (4, 1),
]  # BipartitenessCheckTest.java:81-90


def test_bipartite_golden():
    stream = EdgeStream.from_collection(BIPARTITE_EDGES, CFG)
    results = stream.aggregate(BipartitenessCheck(window_ms=500)).collect()
    assert [str(r[0]) for r in results] == [
        "(true,{1={1=(1,true), 2=(2,false), 3=(3,false), 4=(4,false), "
        "5=(5,true), 7=(7,true), 9=(9,true)}})"
    ]


def test_non_bipartite_golden():
    stream = EdgeStream.from_collection(NON_BIPARTITE_EDGES, CFG)
    results = stream.aggregate(BipartitenessCheck(window_ms=500)).collect()
    assert [str(r[0]) for r in results] == ["(false,{})"]


def test_bipartite_batched_matches_sequential():
    for bs in (1, 3, 6):
        stream = EdgeStream.from_collection(BIPARTITE_EDGES, CFG, batch_size=bs)
        results = stream.aggregate(BipartitenessCheck(window_ms=500)).collect()
        assert str(results[-1][0]).startswith("(true,")

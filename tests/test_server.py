"""Streaming RPC serving plane (ISSUE 8): the network frontend on the
multi-tenant job runtime.

The contracts under test:

* EQUIVALENCE — N remote clients streaming wire batches concurrently over
  loopback produce emission leaves BIT-IDENTICAL to the same jobs run
  in-process (windowed / async / owner-sharded planes; fixed-width and
  BDV wire formats), and warmed same-shape remote jobs compile nothing.
* ROBUSTNESS — garbage, truncated, and oversized frames get a clean error
  frame (never a hang or a traceback-closed socket); wire buffers failing
  the ``from_wire`` guards are refused per buffer with the connection kept
  alive.
* RECOVERY — drain replies with checkpoint-derived resume cursors;
  SIGKILL the server mid-stream, restart, reconnect: the client resumes
  from the cursor with exact non-idempotent counts and overlap-only
  emissions (the at-least-once contract checkpoints already pin).
* TENANCY — token auth, per-tenant admission caps and scheduler weights,
  per-tenant observability counters.

Every test carries ``timeout_cap``: a wedged scheduler, a blocking pull
on a starved socket, or a hung drain must FAIL, not wedge tier-1.
"""

import os
import signal
import socket
import struct
import subprocess
import sys
import threading
import time

import numpy as np
import pytest

from gelly_streaming_tpu.core.config import (
    RuntimeConfig,
    ServerConfig,
    StreamConfig,
    TenantConfig,
)
from gelly_streaming_tpu.core.stream import EdgeStream
from gelly_streaming_tpu.core.types import EdgeBatch
from gelly_streaming_tpu.io.sources import NetworkEdgeSource, SourceQuiesced
from gelly_streaming_tpu.library.connected_components import (
    ConnectedComponents,
)
from gelly_streaming_tpu.runtime import JobManager, JobState
from gelly_streaming_tpu.runtime import protocol
from gelly_streaming_tpu.runtime.client import (
    ClientError,
    GellyClient,
    ServerRefused,
)
from gelly_streaming_tpu.runtime.server import (
    StreamServer,
    _TokenBucket,
    record_leaves,
)
from gelly_streaming_tpu.utils import metrics

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

pytestmark = pytest.mark.timeout_cap(300)

CAP = 1 << 12
W = 1 << 10
B = 1 << 9
N = 4 * W


def _graph(seed: int, n: int = N, cap: int = CAP):
    rng = np.random.default_rng(seed)
    return (
        rng.integers(0, cap, n).astype(np.int32),
        rng.integers(0, cap, n).astype(np.int32),
    )


def _batches_stream(src, dst, cfg, batch):
    """The in-process twin of a remote push job: the identical decoded
    batch sequence through the identical windowed planes."""

    def factory():
        for i in range(0, len(src), batch):
            yield EdgeBatch.from_arrays(
                src[i : i + batch], dst[i : i + batch], pad_to=batch
            )

    return EdgeStream.from_batches(factory, cfg)


def _oracle_leaves(src, dst, cfg, batch, descriptor=None):
    out = _batches_stream(src, dst, cfg, batch).aggregate(
        descriptor or ConnectedComponents()
    )
    return [record_leaves(rec) for rec in out]


def _assert_leaves_equal(want, got, label=""):
    assert len(want) == len(got), (label, len(want), len(got))
    for w, (a, b) in enumerate(zip(want, got)):
        assert len(a) == len(b), (label, w)
        for x, y in zip(a, b):
            assert np.array_equal(x, y), f"{label} window {w} diverged"


# ---------------------------------------------------------------------------
# equivalence: remote == in-process, bit-identical
# ---------------------------------------------------------------------------


def test_four_remote_clients_stream_bit_identical_concurrently():
    """4 clients, each its own connection/thread/dataset, streaming
    concurrently: every job's emission leaves equal the in-process run of
    the same batches — and (CC on these planes) the from_arrays wire fast
    path too, so the remote plane is anchored to the user-facing oracle."""
    cfg = StreamConfig(
        vertex_capacity=CAP, batch_size=B, ingest_window_edges=W
    )
    datasets = [_graph(seed) for seed in range(4)]
    oracles = [_oracle_leaves(s, d, cfg, B) for s, d in datasets]
    results = [None] * 4
    errors = []
    with JobManager() as jm, StreamServer(jm, ServerConfig()) as server:

        def run_client(i):
            try:
                s, d = datasets[i]
                with GellyClient("127.0.0.1", server.port) as c:
                    c.submit(
                        name=f"cc-{i}",
                        query="cc",
                        capacity=CAP,
                        window_edges=W,
                        batch=B,
                    )
                    c.push_edges(
                        f"cc-{i}", s, d, batch=B, capacity=CAP, bdv=(i % 2 == 1)
                    )
                    results[i] = list(
                        c.iter_results(f"cc-{i}", deadline_s=240)
                    )
            except BaseException as e:  # surfaced on the main thread
                errors.append((i, e))

        threads = [
            threading.Thread(target=run_client, args=(i,)) for i in range(4)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=280)
    assert not errors, errors
    for i in range(4):
        _assert_leaves_equal(oracles[i], results[i], f"client {i}")
    # anchor to the independent from_arrays oracle (the wire fast path):
    # parent arrays must agree value-for-value across planes
    s, d = datasets[0]
    wire = [
        np.asarray(rec[0].parent)
        for rec in EdgeStream.from_arrays(s, d, cfg).aggregate(
            ConnectedComponents()
        )
    ]
    got = [leaves[1] for leaves in results[0]]  # [capacity, parent, seen]
    for a, b in zip(wire, got):
        assert np.array_equal(a, b)


def test_remote_async_and_sharded_planes_match_oracle():
    s, d = _graph(7)
    for name, kwargs in (
        ("async", {"async_windows": 2}),
        ("sharded", {"num_shards": 2}),
    ):
        cfg = StreamConfig(
            vertex_capacity=CAP, batch_size=B, ingest_window_edges=W, **kwargs
        )
        oracle = _oracle_leaves(s, d, cfg, B)
        with JobManager() as jm, StreamServer(jm, ServerConfig()) as server:
            with GellyClient("127.0.0.1", server.port) as c:
                c.submit(
                    name=name,
                    query="cc",
                    capacity=CAP,
                    window_edges=W,
                    batch=B,
                    **kwargs,
                )
                c.push_edges(name, s, d, batch=B, capacity=CAP)
                got = list(c.iter_results(name, deadline_s=240))
        _assert_leaves_equal(oracle, got, name)


def test_warmed_same_shape_remote_jobs_compile_nothing():
    from gelly_streaming_tpu.core import compile_cache

    cfg = StreamConfig(
        vertex_capacity=CAP, batch_size=B, ingest_window_edges=W
    )
    warm_s, warm_d = _graph(29)
    _oracle_leaves(warm_s, warm_d, cfg, B)  # the warmup pays the compiles
    compile_cache.reset_stats()
    datasets = [_graph(seed) for seed in (31, 37)]
    with JobManager() as jm, StreamServer(jm, ServerConfig()) as server:
        with GellyClient("127.0.0.1", server.port) as c:
            for i, (s, d) in enumerate(datasets):
                c.submit(
                    name=f"warm-{i}",
                    query="cc",
                    capacity=CAP,
                    window_edges=W,
                    batch=B,
                )
                c.push_edges(f"warm-{i}", s, d, batch=B, capacity=CAP)
            for i in range(2):
                assert list(c.iter_results(f"warm-{i}", deadline_s=240))
    stats = compile_cache.stats()
    assert stats["recompiles"] == 0, stats
    assert stats["compiles"] == 0, (
        "warmed same-shape remote jobs should reuse executables outright",
        stats,
    )


# ---------------------------------------------------------------------------
# protocol robustness: refusal, never a hang or a dirty close
# ---------------------------------------------------------------------------


def _raw_conn(port):
    sock = socket.create_connection(("127.0.0.1", port), timeout=30)
    return sock, sock.makefile("rwb")


def test_garbage_frame_gets_clean_error_frame_then_close():
    with JobManager() as jm, StreamServer(jm, ServerConfig()) as server:
        sock, f = _raw_conn(server.port)
        f.write(b"ZZZZ" + b"\x00" * 64)
        f.flush()
        reply = protocol.read_frame(f)
        assert reply is not None
        head, _ = reply
        assert head["ok"] is False and head["code"] == "bad-frame"
        assert f.read(1) == b""  # server closed its side cleanly
        sock.close()
        # the listener is unharmed: a fresh connection works
        with GellyClient("127.0.0.1", server.port) as c:
            assert c.ping()["ok"]


def test_oversized_payload_refused_with_error_frame():
    srv_cfg = ServerConfig(max_frame_bytes=1 << 14)
    with JobManager() as jm, StreamServer(jm, srv_cfg) as server:
        sock, f = _raw_conn(server.port)
        head = b'{"verb":"push"}'
        f.write(
            protocol.MAGIC
            + struct.pack(">II", len(head), (1 << 14) + 1)[0:8]
        )
        f.write(head)
        f.flush()
        reply = protocol.read_frame(f)
        head_r, _ = reply
        assert head_r["ok"] is False and head_r["code"] == "frame-too-large"
        sock.close()


def test_truncated_frame_and_undecodable_header_are_survivable():
    with JobManager() as jm, StreamServer(jm, ServerConfig()) as server:
        # truncated: half a prefix then hangup — nothing to reply to
        sock, f = _raw_conn(server.port)
        f.write(protocol.MAGIC + b"\x00\x00")
        f.flush()
        sock.close()
        # undecodable JSON header
        sock2, f2 = _raw_conn(server.port)
        bad = b"\xff\xfenot json"
        f2.write(protocol.MAGIC + struct.pack(">II", len(bad), 0) + bad)
        f2.flush()
        head, _ = protocol.read_frame(f2)
        assert head["ok"] is False and head["code"] == "bad-frame"
        sock2.close()
        # server still healthy
        with GellyClient("127.0.0.1", server.port) as c:
            assert c.ping()["ok"]


def test_bad_wire_buffers_refused_per_buffer_connection_survives():
    from gelly_streaming_tpu.io import wire as wire_mod

    metrics.reset_tenant_stats()
    with JobManager() as jm, StreamServer(jm, ServerConfig()) as server:
        with GellyClient("127.0.0.1", server.port) as c:
            c.submit(
                name="j", query="cc", capacity=CAP, window_edges=W, batch=B
            )
            # wrong size for the fixed width
            with pytest.raises(ServerRefused, match="holds") as e:
                c.push_wire("j", np.zeros(7, np.uint8))
            assert e.value.code == "bad-wire"
            # out-of-range ids (width 2 can express ids >= CAP=4096)
            s = np.full(B, CAP + 5, np.int32)
            buf = wire_mod.pack_edges(s, s, 2)
            with pytest.raises(ServerRefused, match="decodes vertex ids"):
                c.push_wire("j", buf)
            # BDV truncated below the per-buffer byte floor
            with pytest.raises(ServerRefused, match="truncated"):
                c.push_wire("j", np.zeros(16, np.uint8), kind="bdv")
            # tail with a count/payload mismatch
            with pytest.raises(ServerRefused, match="tail payload"):
                c.call(
                    {"verb": "push", "job": "j", "kind": "tail", "count": 8},
                    np.zeros(4, "<i4").tobytes(),
                )
            # unknown job / unknown verb are typed refusals
            with pytest.raises(ServerRefused) as e2:
                c.push_wire("nope", np.zeros(4 * B, np.uint8))
            assert e2.value.code == "unknown-job"
            with pytest.raises(ServerRefused) as e3:
                c.call({"verb": "frobnicate"})
            assert e3.value.code == "unknown-verb"
            # the connection survived every refusal; the job still works
            src, dst = _graph(3)
            c.push_edges("j", src, dst, batch=B, capacity=CAP)
            assert len(list(c.iter_results("j", deadline_s=240))) == N // W
    rejects = metrics.tenant_totals()["tenant_ingest_rejects"]
    assert rejects >= 3, rejects


# ---------------------------------------------------------------------------
# isolation: a dead/idle client starves only its own job
# ---------------------------------------------------------------------------


def test_push_to_terminal_job_refused_not_wedged():
    """A cancelled job's generator never drains its ingest queue again;
    a client that keeps pushing must get a typed refusal once the queue
    fills — never a forever-blocked connection thread."""
    from gelly_streaming_tpu.io import wire as wire_mod

    srv_cfg = ServerConfig(ingest_queue_batches=4)
    with JobManager() as jm, StreamServer(jm, srv_cfg) as server:
        with GellyClient("127.0.0.1", server.port) as c:
            # a window the pushes below never close: the job stays PENDING
            c.submit(
                name="t", query="cc", capacity=CAP, window_edges=1 << 20,
                batch=B,
            )
            assert c.cancel("t")["state"] == JobState.CANCELLED
            s = np.zeros(B, np.int32)
            buf = wire_mod.pack_edges(s, s, 2)
            with pytest.raises(ServerRefused) as e:
                for _ in range(8):  # queue cap 4: the 5th+ must refuse
                    c.push_wire("t", buf)
            assert e.value.code == "terminal"


def test_dead_client_starves_only_its_own_job():
    metrics.reset_job_stats()
    with JobManager() as jm, StreamServer(jm, ServerConfig()) as server:
        dead = GellyClient("127.0.0.1", server.port)
        dead.submit(
            name="starved", query="cc", capacity=CAP, window_edges=W, batch=B
        )
        # push HALF a window, then vanish without eos: the job must never
        # block the scheduler round
        s, d = _graph(41, n=W // 2)
        dead.push_edges(
            "starved", s, d, batch=B, capacity=CAP, close=False
        )
        dead.close()
        with GellyClient("127.0.0.1", server.port) as c:
            src, dst = _graph(43)
            c.submit(
                name="live", query="cc", capacity=CAP, window_edges=W, batch=B
            )
            c.push_edges("live", src, dst, batch=B, capacity=CAP)
            got = list(c.iter_results("live", deadline_s=240))
            assert len(got) == N // W
            status = c.status()
        row = status["status"]["jobs"]["default/starved"]
        assert row["state"] in ("PENDING", "RUNNING")
        # the gate skipped the starved job's rounds instead of pulling
        assert (
            metrics.job_stats("default/starved")["job_source_wait_skips"] >= 1
        )


# ---------------------------------------------------------------------------
# drain -> restart -> resume (graceful), and the status verb
# ---------------------------------------------------------------------------


def test_drain_replies_cursors_and_restart_resumes_exactly(tmp_path):
    srv_cfg = ServerConfig(checkpoint_prefix=str(tmp_path / "ck"))
    src, dst = _graph(11)
    serial = [(i + 1) * W for i in range(N // W)]
    first = []
    with JobManager() as jm, StreamServer(jm, srv_cfg) as server:
        with GellyClient("127.0.0.1", server.port) as c:
            c.submit(
                name="cnt",
                query="edges",
                capacity=CAP,
                window_edges=W,
                batch=B,
                checkpoint=True,
            )
            # also an async-windowed job mid-flight: its in-flight windows
            # must flush through the completion-queue path, not wedge drain
            c.submit(
                name="afly",
                query="cc",
                capacity=CAP,
                window_edges=W,
                batch=B,
                async_windows=2,
            )
            c.push_edges(
                "afly", *_graph(13, n=2 * W), batch=B, capacity=CAP,
                close=False,
            )
            half = 2 * W + W // 2
            c.push_edges(
                "cnt", src[:half], dst[:half], batch=B, capacity=CAP,
                close=False,
            )
            deadline = time.monotonic() + 120
            while len(first) < 2 and time.monotonic() < deadline:
                recs, _state, _eos = c.results("cnt", timeout_ms=2000)
                first.extend(int(r[0]) for r in recs)
            assert len(first) >= 2
            t0 = time.monotonic()
            reply = c.drain()
            assert time.monotonic() - t0 < 90  # flush, not wedge
            cur = reply["cursors"]["cnt"]
            assert cur["state"] == "CANCELLED"
            # the cursor is whole saved windows, behind or at the emissions
            assert cur["resume_edges"] is not None
            assert cur["resume_edges"] % W == 0
            assert 0 < cur["resume_edges"] <= len(first) * W
            assert reply["cursors"]["afly"]["state"] == "CANCELLED"
            # a quiesced source refuses further pushes loudly — and a
            # refusal mid-PIPELINE (several frames in flight) must leave
            # the connection in sync: the next verb still works
            with pytest.raises(ServerRefused) as e:
                c.push_edges(
                    "cnt", src, dst, batch=B, capacity=CAP, close=False,
                )
            assert e.value.code == "quiesced"
            assert c.status()["ok"]
    # "restart": a fresh manager + server over the same checkpoint prefix
    with JobManager() as jm, StreamServer(jm, srv_cfg) as server:
        with GellyClient("127.0.0.1", server.port) as c:
            rep = c.submit(
                name="cnt",
                query="edges",
                capacity=CAP,
                window_edges=W,
                batch=B,
                checkpoint=True,
            )
            assert rep["resume_edges"] == cur["resume_edges"]
            c.push_edges(
                "cnt", src, dst, batch=B, capacity=CAP,
                start=rep["resume_edges"],
            )
            second = [
                int(r[0]) for r in c.iter_results("cnt", deadline_s=240)
            ]
    # overlap-only emissions; the non-idempotent final count is exact
    overlap = len(first) + len(second) - len(serial)
    assert overlap >= 0, "drain/resume dropped emissions (a gap)"
    assert first[: len(first) - overlap] + second == serial
    assert second[-1] == N


def test_status_verb_reuses_serve_status_lines_and_tenant_stats():
    from gelly_streaming_tpu.runtime.serve import _status_lines

    metrics.reset_tenant_stats()
    with JobManager() as jm, StreamServer(jm, ServerConfig()) as server:
        with GellyClient("127.0.0.1", server.port) as c:
            c.submit(
                name="st", query="cc", capacity=CAP, window_edges=W, batch=B
            )
            s, d = _graph(17)
            c.push_edges("st", s, d, batch=B, capacity=CAP)
            assert list(c.iter_results("st", deadline_s=240))
            reply = c.status()
    # the verb ships the SAME renderer's lines (no duplicated formatter)
    assert reply["lines"] == _status_lines(reply["status"])
    assert any("default/st" in line for line in reply["lines"])
    ten = reply["tenants"]["default"]
    assert ten["tenant_requests"] > 0
    assert ten["tenant_ingest_edges"] == N
    assert ten["tenant_ingest_wire_bytes"] > 0
    assert ten["tenant_ingest_raw_bytes"] == 8 * N
    assert ten["tenant_jobs_submitted"] == 1
    assert reply["server"]["connections"] >= 1


# ---------------------------------------------------------------------------
# tenancy: auth, quotas, priority
# ---------------------------------------------------------------------------

_TENANTS = (
    TenantConfig(tenant="alpha", token="tok-a", max_jobs=1, weight=3),
    TenantConfig(
        tenant="beta", token="tok-b", max_state_bytes=1, max_ingest_bps=0
    ),
)


def test_tenant_auth_and_quota_enforcement():
    metrics.reset_tenant_stats()
    srv_cfg = ServerConfig(tenants=_TENANTS)
    with JobManager() as jm, StreamServer(jm, srv_cfg) as server:
        # missing/unknown token refused before any verb runs
        with GellyClient("127.0.0.1", server.port, token="wrong") as c:
            with pytest.raises(ServerRefused) as e:
                c.ping()
            assert e.value.code == "auth"
        with GellyClient("127.0.0.1", server.port, token="tok-a") as c:
            rep = c.submit(
                name="one",
                query="cc",
                capacity=CAP,
                window_edges=W,
                batch=B,
                weight=2,
            )
            # tenant weight multiplies job weight in the fair scheduler
            assert rep["weight"] == 6
            with pytest.raises(ServerRefused) as e:
                c.submit(
                    name="two",
                    query="cc",
                    capacity=CAP,
                    window_edges=W,
                    batch=B,
                )
            assert e.value.code == "admission"
            # alpha's namespace is its own: beta can reuse the name, but
            # beta's 1-byte state cap refuses any real summary
            with GellyClient(
                "127.0.0.1", server.port, token="tok-b"
            ) as cb:
                with pytest.raises(ServerRefused) as eb:
                    cb.submit(
                        name="one",
                        query="cc",
                        capacity=CAP,
                        window_edges=W,
                        batch=B,
                    )
                assert eb.value.code == "admission"
            s, d = _graph(19)
            c.push_edges("one", s, d, batch=B, capacity=CAP)
            assert list(c.iter_results("one", deadline_s=240))
            # status is tenant-scoped: alpha sees only alpha's jobs and
            # only alpha's counters — no cross-tenant disclosure
            view = c.status()
            assert all(
                k.startswith("alpha/") for k in view["status"]["jobs"]
            )
            assert set(view["tenants"]) == {"alpha"}
    stats = metrics.all_tenant_stats()
    assert stats["alpha"]["tenant_admission_rejections"] == 1
    assert stats["beta"]["tenant_admission_rejections"] == 1
    assert stats["alpha"]["tenant_ingest_edges"] == N


def test_token_bucket_math():
    bucket = _TokenBucket(1000)
    assert bucket.reserve(500) == 0.0
    assert bucket.reserve(500) == 0.0  # the 1-second burst allowance
    sleep_s = bucket.reserve(1000)
    assert sleep_s > 0.5  # ~1s of debt at 1000 B/s
    assert _TokenBucket(0).reserve(1 << 30) == 0.0  # unlimited


def test_tenant_ingest_rate_limit_throttles_connection():
    metrics.reset_tenant_stats()
    tenants = (
        TenantConfig(tenant="slow", token="tok-s", max_ingest_bps=16384),
    )
    with JobManager() as jm, StreamServer(
        jm, ServerConfig(tenants=tenants)
    ) as server:
        with GellyClient("127.0.0.1", server.port, token="tok-s") as c:
            c.submit(
                name="rl", query="cc", capacity=CAP, window_edges=W, batch=B
            )
            s, d = _graph(23, n=2 * W)
            c.push_edges("rl", s, d, batch=B, capacity=CAP)
            assert list(c.iter_results("rl", deadline_s=240))
    # 2048 edges at 4 B/edge (width 2) = 8 KiB wire > the 16 KiB burst
    # only partially — but the accounting must prove the limiter engaged
    # on the byte ledger even when no sleep happened
    stats = metrics.tenant_stats("slow")
    assert stats["tenant_ingest_wire_bytes"] >= 4 * 2 * W


# ---------------------------------------------------------------------------
# NetworkEdgeSource units: the ready() gate and the push guards
# ---------------------------------------------------------------------------


def test_network_source_ready_accounting_and_resume():
    cfg = StreamConfig(
        vertex_capacity=64, batch_size=16, ingest_window_edges=32
    )
    src = NetworkEdgeSource(cfg, 16)
    assert not src.ready()  # empty
    from gelly_streaming_tpu.io import wire as wire_mod

    buf = wire_mod.pack_edges(
        np.arange(16, dtype=np.int32), np.arange(16, dtype=np.int32), 2
    )
    for _ in range(2):  # one full window queued, boundary edge not yet
        src.push_wire(buf, 2)
    assert not src.ready()
    src.push_wire(buf, 2)  # first edge of window 1 arrives: closable
    assert src.ready()
    # closed: always ready (drain everything, then end-of-stream)
    src.close()
    assert src.ready()
    with pytest.raises(SourceQuiesced):
        src.push_wire(buf, 2)
    # resume: filler windows never make the source ready on their own
    res = NetworkEdgeSource(cfg, 16, resume_edges=64)
    assert not res.ready()
    res.push_wire(buf, 2)  # 16 real edges: window 2 not yet closable
    assert not res.ready()
    for _ in range(2):
        res.push_wire(buf, 2)
    assert res.ready()  # edge 96 arrived: window 2 closable
    # quiesce freezes scheduling and refuses pushes
    res.quiesce()
    assert not res.ready()
    with pytest.raises(SourceQuiesced):
        res.push_wire(buf, 2)
    # misaligned cursors and window-spanning batches are refused loudly
    with pytest.raises(ValueError, match="multiple"):
        NetworkEdgeSource(cfg, 16, resume_edges=48)
    with pytest.raises(ValueError, match="must be <="):
        NetworkEdgeSource(cfg, 64)


def test_network_source_tail_guards():
    cfg = StreamConfig(vertex_capacity=64, batch_size=16)
    src = NetworkEdgeSource(cfg, 16)
    with pytest.raises(ValueError, match="intern ids first"):
        src.push_tail(np.array([99], np.int64), np.array([1], np.int64))
    with pytest.raises(ValueError, match="1..16"):
        src.push_tail(np.zeros(17, np.int32), np.zeros(17, np.int32))
    assert src.push_tail([1, 2], [3, 4]) == 2


# ---------------------------------------------------------------------------
# SIGKILL the server mid-stream; restart; reconnect; resume
# ---------------------------------------------------------------------------


def _spawn_listen_server(tmp_path, extra_env=None):
    env = dict(
        os.environ,
        XLA_FLAGS="--xla_force_host_platform_device_count=1",
        JAX_PLATFORMS="cpu",
        PYTHONPATH=REPO + os.pathsep + os.environ.get("PYTHONPATH", ""),
        **(extra_env or {}),
    )
    proc = subprocess.Popen(
        [
            sys.executable,
            "-m",
            "gelly_streaming_tpu.runtime.serve",
            "--listen",
            "127.0.0.1:0",
            "--checkpoint-prefix",
            str(tmp_path / "ck"),
            "--status-interval",
            "0",
        ],
        env=env,
        stderr=subprocess.PIPE,
        stdout=subprocess.PIPE,
    )
    port = None
    deadline = time.monotonic() + 120
    while time.monotonic() < deadline:
        line = proc.stderr.readline().decode()
        if "listening on" in line:
            port = int(line.rsplit(":", 1)[1])
            break
        if not line and proc.poll() is not None:
            break
    assert port, "server child never reported its port"
    return proc, port


@pytest.mark.timeout_cap(600)
def test_sigkill_server_restart_client_resumes_from_cursor(tmp_path):
    src, dst = _graph(47)
    serial = [(i + 1) * W for i in range(N // W)]

    proc, port = _spawn_listen_server(tmp_path)
    first = []
    try:
        with GellyClient("127.0.0.1", port) as c:
            c.submit(
                name="kill",
                query="edges",
                capacity=CAP,
                window_edges=W,
                batch=B,
                checkpoint=True,
            )
            half = 3 * W
            c.push_edges(
                "kill", src[:half], dst[:half], batch=B, capacity=CAP,
                close=False,
            )
            deadline = time.monotonic() + 180
            while len(first) < 2 and time.monotonic() < deadline:
                recs, _state, _eos = c.results("kill", timeout_ms=2000)
                first.extend(int(r[0]) for r in recs)
        assert len(first) >= 2
    finally:
        proc.kill()  # SIGKILL: no drain, no cleanup, no atexit
        proc.wait(timeout=30)

    proc2, port2 = _spawn_listen_server(tmp_path)
    try:
        with GellyClient("127.0.0.1", port2) as c:
            rep = c.submit(
                name="kill",
                query="edges",
                capacity=CAP,
                window_edges=W,
                batch=B,
                checkpoint=True,
            )
            # the cursor came from the dead process's checkpoint
            assert rep["resume_edges"] > 0
            assert rep["resume_edges"] % W == 0
            c.push_edges(
                "kill", src, dst, batch=B, capacity=CAP,
                start=rep["resume_edges"],
            )
            second = [
                int(r[0]) for r in c.iter_results("kill", deadline_s=240)
            ]
            # remote shutdown ends the --listen loop cleanly
            c.drain(shutdown=True)
        assert proc2.wait(timeout=60) == 0
    finally:
        if proc2.poll() is None:
            proc2.kill()
            proc2.wait(timeout=30)
    overlap = len(first) + len(second) - len(serial)
    assert overlap >= 0, "SIGKILL/restart dropped emissions (a gap)"
    assert first[: len(first) - overlap] + second == serial
    assert second[-1] == N  # exact non-idempotent count: state exactly-once


# ---------------------------------------------------------------------------
# gelly-client console script against a live server
# ---------------------------------------------------------------------------


def test_gelly_client_console_flow(capsys):
    from gelly_streaming_tpu.runtime import client as client_mod

    with JobManager() as jm, StreamServer(jm, ServerConfig()) as server:
        addr = f"127.0.0.1:{server.port}"
        assert (
            client_mod.main(
                [
                    "--connect",
                    addr,
                    "submit",
                    "--name",
                    "cli",
                    "--query",
                    "edges",
                    "--capacity",
                    str(CAP),
                    "--window-edges",
                    str(W),
                    "--batch",
                    str(B),
                ]
            )
            == 0
        )
        assert (
            client_mod.main(
                [
                    "--connect",
                    addr,
                    "push-edges",
                    "--job",
                    "cli",
                    "--edges",
                    str(N),
                    "--capacity",
                    str(CAP),
                    "--batch",
                    str(B),
                ]
            )
            == 0
        )
        assert client_mod.main(["--connect", addr, "status"]) == 0
        assert client_mod.main(["--connect", addr, "drain"]) == 0
    out = capsys.readouterr().out
    assert "submitted cli" in out
    assert "end of stream" in out
    assert "default/cli" in out


def test_client_deadline_fails_loudly_not_forever():
    with JobManager() as jm, StreamServer(jm, ServerConfig()) as server:
        with GellyClient("127.0.0.1", server.port) as c:
            c.submit(
                name="idle", query="cc", capacity=CAP, window_edges=W, batch=B
            )
            with pytest.raises(ClientError, match="no end-of-stream"):
                for _ in c.iter_results(
                    "idle", poll_timeout_ms=100, deadline_s=1.0
                ):
                    pass


# ---------------------------------------------------------------------------
# observability plane (ISSUE 9): metrics / trace verbs, FAILED post-mortems,
# gelly-top


def _push_one_job(server, name, seed=0, trace_sample=0.0, token=""):
    s, d = _graph(seed)
    with GellyClient("127.0.0.1", server.port, token=token) as c:
        spec = dict(
            name=name, query="cc", capacity=CAP, window_edges=W, batch=B
        )
        if trace_sample:
            spec["trace_sample"] = trace_sample
        c.submit(**spec)
        c.push_edges(name, s, d, batch=B, capacity=CAP)
        return list(c.iter_results(name, deadline_s=240))


def test_metrics_verb_returns_histograms_and_prometheus():
    metrics.reset_histograms()
    with JobManager() as jm, StreamServer(jm, ServerConfig()) as server:
        recs = _push_one_job(server, "obs")
        assert recs
        with GellyClient("127.0.0.1", server.port) as c:
            snap = c.metrics()
            # the four canonical histograms all saw this job
            job_rows = snap["histograms"]["jobs"]["default/obs"]
            for name in (
                "submit_to_first_emission_ms",
                "window_close_to_emission_ms",
                "push_to_fold_ms",
                "sched_queue_wait_ms",
            ):
                assert job_rows[name]["count"] > 0, name
                assert job_rows[name]["p99_ms"] >= job_rows[name]["p50_ms"]
            # per-tenant submit-to-first row (stamped at the server sink)
            t_row = snap["histograms"]["tenants"]["default"]
            assert t_row["submit_to_first_emission_ms"]["count"] == 1
            # process planes ride along
            assert snap["pipeline"]["pipeline_windows_drained"] >= 0
            assert "recompiles" in snap["compile_cache"]
            # prometheus text renders the same registry
            text = c.metrics_prometheus()
            assert 'gelly_job_records{job="default/obs"}' in text
            assert "gelly_submit_to_first_emission_ms_count" in text
            assert 'le="+Inf"' in text


def test_metrics_verb_is_tenant_scoped():
    cfg = ServerConfig(
        tenants=(
            TenantConfig(tenant="a", token="tok-a"),
            TenantConfig(tenant="b", token="tok-b"),
        )
    )
    metrics.reset_histograms()
    with JobManager() as jm, StreamServer(jm, cfg) as server:
        _push_one_job(server, "mine", token="tok-a")
        with GellyClient("127.0.0.1", server.port, token="tok-b") as c:
            snap = c.metrics()
            # tenant b sees none of tenant a's jobs, rows, or histograms
            assert snap["jobs"] == {}
            assert snap["job_totals"] == {}
            assert list(snap["tenants"]) == ["b"]
            assert snap["histograms"]["jobs"] == {}
            assert snap["histograms"]["tenants"] == {}
        with GellyClient("127.0.0.1", server.port, token="tok-a") as c:
            snap = c.metrics()
            assert "a/mine" in snap["jobs"]
            assert "a/mine" in snap["histograms"]["jobs"]


def test_trace_verb_dumps_sampled_spans():
    from gelly_streaming_tpu.utils import tracing

    tracing.reset_tracing()
    with JobManager() as jm, StreamServer(jm, ServerConfig()) as server:
        recs = _push_one_job(server, "traced", trace_sample=1.0)
        with GellyClient("127.0.0.1", server.port) as c:
            reply = c.trace(64)
            assert reply["tracing_active"]
            spans = reply["spans"]
            assert len(spans) >= len(recs)
            stages = {s["stage"] for s in spans[-1]["stages"]}
            assert "dispatch" in stages and "queued" in stages
            # the per-stage aggregates the metrics verb exposes: stage sums
            # equal the total wall clock (the queued residual closes the
            # gap by construction)
            agg = c.metrics()["spans"]["stages"]
            plane = next(iter(agg.values()))
            attributed = sum(
                v["total_ms"] for k, v in plane.items() if k != "total"
            )
            assert attributed == pytest.approx(
                plane["total"]["total_ms"], rel=0.10
            )
    tracing.reset_tracing()


def test_failed_job_status_carries_flight_recorder_dump():
    from gelly_streaming_tpu.utils import tracing

    # tracing must be ACTIVE for the dump (a process that never traced
    # has nothing to dump); activate it and seed one span
    tracing.sampler(StreamConfig(trace_sample=1.0), "seed")
    span = tracing.WindowSpan(999_999, "seed", 7)
    tracing.flight_recorder().record(span)

    def bad_build():
        def it():
            yield (np.zeros(4),)
            raise RuntimeError("kaboom")

        return it()

    with JobManager() as jm:
        job = jm.submit(bad_build, name="doomed")
        job.wait(60)
        assert job.state == JobState.FAILED
        row = jm.status()["jobs"]["doomed"]
        assert row["error"] is not None
        assert isinstance(row["trace"], list) and row["trace"]
        assert any(s["trace_id"] == 999_999 for s in row["trace"])
    tracing.reset_tracing()


def test_gelly_top_once_renders_live_server(capsys):
    from gelly_streaming_tpu.runtime import top as top_mod

    with JobManager() as jm, StreamServer(jm, ServerConfig()) as server:
        _push_one_job(server, "topjob")
        rc = top_mod.main(
            ["--connect", f"127.0.0.1:{server.port}", "--once"]
        )
    assert rc == 0
    out = capsys.readouterr().out
    assert "gelly-top" in out
    assert "default/topjob" in out
    assert "DONE" in out
    assert "TENANT" in out and "default" in out


def test_gelly_top_render_frame_computes_eps():
    from gelly_streaming_tpu.runtime.top import render_frame

    status = {
        "server": {"connections": 1, "served_jobs": 1, "port": 1234},
        "status": {
            "jobs": {
                "t/j": {
                    "state": "RUNNING",
                    "job_records": 10,
                    "job_edges": 20_000,
                    "queue_depth": 2,
                }
            }
        },
    }
    snap = {
        "pipeline": {},
        "spans": {},
        "tenants": {},
        "histograms": {
            "jobs": {
                "t/j": {
                    "window_close_to_emission_ms": {
                        "count": 10,
                        "p50_ms": 1.5,
                        "p99_ms": 9.0,
                    },
                    "submit_to_first_emission_ms": {
                        "count": 1,
                        "p50_ms": 42.0,
                        "p99_ms": 42.0,
                    },
                }
            }
        },
    }
    lines = render_frame(status, snap, {"t/j": 10_000}, 2.0)
    row = next(l for l in lines if l.startswith("t/j"))
    assert "RUNNING" in row
    assert "5.0k" in row  # (20000 - 10000) / 2.0 s
    assert "1.5/9.0" in row
    assert "42.0" in row

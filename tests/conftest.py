"""Test configuration: force a virtual 8-device CPU mesh before jax imports.

This is the MiniCluster analog (SURVEY.md §4): the reference tests "distributed"
execution on an in-JVM Flink MiniCluster with multiple task slots; here we test
multi-shard SPMD on one host by splitting the CPU backend into 8 XLA devices.
Must run before jax initializes, hence module-level in conftest.
"""

import os
import sys

# XLA's CPU client sizes its worker pools from the detected core count (1
# here); with 8 virtual devices the partitions' blocking collective waits
# can then hold every pool worker — a schedule-dependent in-process
# DEADLOCK (observed: rare multi-minute stalls / 40 s-timeout aborts on
# ppermute-heavy tests).  NPROC is the pool-size override the client
# honors: 16 workers mean 8 waiting partitions can never exhaust the pool.
os.environ.setdefault("NPROC", "16")

_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    _flags = (_flags + " --xla_force_host_platform_device_count=8").strip()
# 8 virtual devices on few (here: one) physical cores: a starved
# partition thread can miss XLA's default 40 s collective rendezvous,
# which abort()s the whole pytest process (observed intermittently on
# the ppermute-heavy mesh tests under host load).  Starvation must be a
# slow test, never suite death.  (Per-flag guards: never shadow a
# user-set value with an appended duplicate.)
if "xla_cpu_collective_call_warn_stuck_timeout_seconds" not in _flags:
    _flags += " --xla_cpu_collective_call_warn_stuck_timeout_seconds=120"
if "xla_cpu_collective_call_terminate_timeout_seconds" not in _flags:
    _flags += " --xla_cpu_collective_call_terminate_timeout_seconds=900"
os.environ["XLA_FLAGS"] = _flags

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# Force CPU even when the session environment preselects a TPU platform (the
# sitecustomize registers an "axon" PJRT backend and pins it regardless of
# JAX_PLATFORMS, so the env var alone is not enough — the config update is).
import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
assert len(jax.devices()) == 8, "expected the virtual 8-device CPU mesh"

"""Test configuration: force a virtual 8-device CPU mesh before jax imports.

This is the MiniCluster analog (SURVEY.md §4): the reference tests "distributed"
execution on an in-JVM Flink MiniCluster with multiple task slots; here we test
multi-shard SPMD on one host by splitting the CPU backend into 8 XLA devices.
Must run before jax initializes, hence module-level in conftest.
"""

import os
import sys

_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# Force CPU even when the session environment preselects a TPU platform (the
# sitecustomize registers an "axon" PJRT backend and pins it regardless of
# JAX_PLATFORMS, so the env var alone is not enough — the config update is).
import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
assert len(jax.devices()) == 8, "expected the virtual 8-device CPU mesh"

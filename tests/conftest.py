"""Test configuration: force a virtual 8-device CPU mesh before jax imports.

This is the MiniCluster analog (SURVEY.md §4): the reference tests "distributed"
execution on an in-JVM Flink MiniCluster with multiple task slots; here we test
multi-shard SPMD on one host by splitting the CPU backend into 8 XLA devices.
Must run before jax initializes, hence module-level in conftest.
"""

import os
import sys

# XLA's CPU client sizes its worker pools from the detected core count (1
# here); with 8 virtual devices the partitions' blocking collective waits
# can then hold every pool worker — a schedule-dependent in-process
# DEADLOCK (observed: rare multi-minute stalls / 40 s-timeout aborts on
# ppermute-heavy tests).  NPROC is the pool-size override the client
# honors: 16 workers mean 8 waiting partitions can never exhaust the pool.
os.environ.setdefault("NPROC", "16")

_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    _flags = (_flags + " --xla_force_host_platform_device_count=8").strip()
# 8 virtual devices on few (here: one) physical cores: a starved
# partition thread can miss XLA's default 40 s collective rendezvous,
# which abort()s the whole pytest process (observed intermittently on
# the ppermute-heavy mesh tests under host load).  Starvation must be a
# slow test, never suite death.  (Per-flag guards: never shadow a
# user-set value with an appended duplicate.)
#
# NOT every XLA build knows these flags — and XLA FATALLY aborts the whole
# process on an unknown XLA_FLAGS entry (parse_flags_from_env.cc), killing
# the suite before pytest prints a byte.  Probe support in a throwaway
# subprocess first and only append the flags a real jax init accepts.


def _xla_accepts(flag: str) -> bool:
    """Probe once per jax version, caching the verdict on disk: the probe
    costs a full cold jax init (~seconds), too much to pay per pytest run."""
    import subprocess
    import tempfile

    try:
        from importlib.metadata import version

        ver = version("jax")
    except Exception:
        ver = "unknown"
    marker = os.path.join(
        tempfile.gettempdir(), f"gelly_xla_flag_probe_{ver}.txt"
    )
    try:
        with open(marker) as f:
            return f.read().strip() == "ok"
    except OSError:
        pass
    env = dict(os.environ, XLA_FLAGS=flag, JAX_PLATFORMS="cpu")
    ok = False
    flag_rejected = False
    try:
        probe = subprocess.run(
            [sys.executable, "-c", "import jax; jax.devices()"],
            env=env,
            capture_output=True,
            timeout=120,
        )
        ok = probe.returncode == 0
        # XLA's unknown-flag abort is the ONE durable negative; anything
        # else (timeout, OOM, load spike) is transient and must be
        # re-probed next run, not cached as a permanent "bad"
        flag_rejected = b"Unknown flags in XLA_FLAGS" in (probe.stderr or b"")
    except Exception:
        pass
    if ok or flag_rejected:
        try:
            with open(marker, "w") as f:
                f.write("ok" if ok else "bad")
        except OSError:
            pass
    return ok


_timeout_flags = [
    "--xla_cpu_collective_call_warn_stuck_timeout_seconds=120",
    "--xla_cpu_collective_call_terminate_timeout_seconds=900",
]
_missing = [
    f
    for f in _timeout_flags
    # per-flag guard: never shadow a user-set value with a duplicate
    if f[2:].split("=")[0] not in _flags
]
if _missing and _xla_accepts(" ".join(_timeout_flags)):
    _flags += " " + " ".join(_missing)
os.environ["XLA_FLAGS"] = _flags

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# Force CPU even when the session environment preselects a TPU platform (the
# sitecustomize registers an "axon" PJRT backend and pins it regardless of
# JAX_PLATFORMS, so the env var alone is not enough — the config update is).
import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
assert len(jax.devices()) == 8, "expected the virtual 8-device CPU mesh"


# ---------------------------------------------------------------------------
# Per-test wall-clock cap for the threaded async-pipeline tests
# (@pytest.mark.timeout_cap(seconds)): a hung completion queue must FAIL the
# test, not wedge the whole tier-1 run.  Same philosophy as the XLA flag
# probe above — capability is PROBED and unsupported configurations degrade
# to running uncapped rather than aborting: the cap needs SIGALRM delivered
# on the main thread (POSIX); when the pytest-timeout plugin is installed it
# owns per-test timeouts and this fixture stands down.

import threading as _threading  # noqa: E402

import pytest  # noqa: E402


@pytest.fixture(autouse=True)
def _timeout_cap(request):
    marker = request.node.get_closest_marker("timeout_cap")
    if marker is None:
        yield
        return
    seconds = float(marker.args[0]) if marker.args else 120.0
    if request.config.pluginmanager.hasplugin("timeout"):
        # pytest-timeout owns per-test timeouts ONLY where one is actually
        # configured for this test (its marker or a global --timeout) —
        # its mere presence must not turn the cap into a silent no-op
        configured = request.node.get_closest_marker("timeout") is not None
        if not configured:
            try:
                configured = float(
                    request.config.getoption("--timeout") or 0
                ) > 0
            except Exception:
                configured = False
        if configured:
            yield
            return
    import signal

    if (
        not hasattr(signal, "SIGALRM")
        or _threading.current_thread() is not _threading.main_thread()
    ):
        yield  # unsupported platform/thread: run uncapped, don't abort
        return

    def _expired(signum, frame):
        raise TimeoutError(
            f"test exceeded its {seconds:.0f}s timeout_cap — a pipeline "
            "thread or completion queue is likely hung"
        )

    old_handler = signal.signal(signal.SIGALRM, _expired)
    signal.setitimer(signal.ITIMER_REAL, seconds)
    try:
        yield
    finally:
        signal.setitimer(signal.ITIMER_REAL, 0)
        signal.signal(signal.SIGALRM, old_handler)

"""The virtual-mesh deadlock workaround (NPROC pool override + raised XLA
collective rendezvous timeouts) lives in BOTH tests/conftest.py and
__graft_entry__.py — they cannot share a helper because each must run before
ANY jax import (importing the package would pull jax).  This drift guard
pins the two copies to the same values."""

import os
import re


def _flags_of(path):
    src = open(path).read()
    vals = dict(
        re.findall(r"--(xla_cpu_collective_call_\w+_timeout_seconds)=(\d+)", src)
    )
    nproc = re.search(r'setdefault\("NPROC", "?(\d+)"?\)', src)
    vals["NPROC"] = nproc.group(1) if nproc else None
    return vals


def test_conftest_and_graft_entry_agree():
    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    a = _flags_of(os.path.join(root, "tests", "conftest.py"))
    b = _flags_of(os.path.join(root, "__graft_entry__.py"))
    assert a == b, (a, b)
    assert a["NPROC"] is not None
    assert set(a) == {
        "NPROC",
        "xla_cpu_collective_call_warn_stuck_timeout_seconds",
        "xla_cpu_collective_call_terminate_timeout_seconds",
    }

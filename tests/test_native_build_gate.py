"""Native build gate (ISSUE 14): a fresh compile of the canonical C++
source must succeed and its entry points must match their numpy twins.

Without this gate, a ``.cpp`` edit that breaks the build (or silently
diverges from a twin) would just drop the whole tree to the numpy
fallback — every native-path test "passes" while the fast path is gone.
Here the library is compiled FRESH into a tmpdir (no sharing with the
mtime-cached build the rest of the suite uses), loaded, and run through
encoder / sorter / reader self-checks against the pure-numpy oracles.
Skips cleanly when the image has no C++ toolchain.
"""

import ctypes
import os
import shutil
import struct
import subprocess

import numpy as np
import pytest

from gelly_streaming_tpu.io import wire

pytestmark = pytest.mark.timeout_cap(240)

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
CANONICAL = os.path.join(
    ROOT, "gelly_streaming_tpu", "native_src", "edge_parser.cpp"
)


@pytest.fixture(scope="module")
def fresh_lib(tmp_path_factory):
    if shutil.which("g++") is None:
        pytest.skip("no C++ toolchain in this image")
    so = str(tmp_path_factory.mktemp("native_gate") / "libgelly_gate.so")
    # the exact flags utils/native.py builds with
    proc = subprocess.run(
        [
            "g++", "-O3", "-shared", "-fPIC", "-std=c++17", "-pthread",
            CANONICAL, "-o", so,
        ],
        capture_output=True,
        text=True,
        timeout=180,
    )
    assert proc.returncode == 0, (
        "canonical native source failed to compile:\n" + proc.stderr
    )
    return ctypes.CDLL(so)


def _i32p(a):
    return a.ctypes.data_as(ctypes.POINTER(ctypes.c_int32))


def _u8p(a):
    return a.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8))


def test_fresh_build_packers_match_numpy_twins(fresh_lib):
    lib = fresh_lib
    lib.pack_edges.restype = ctypes.c_int64
    lib.pack_edges40.restype = ctypes.c_int64
    lib.encode_edges_bdv.restype = ctypes.c_int64
    rng = np.random.default_rng(1)
    n = 513
    for cap, width in [(1 << 16, 2), (1 << 24, 3), (1 << 26, 4)]:
        s = rng.integers(0, cap, n).astype(np.int32)
        d = rng.integers(0, cap, n).astype(np.int32)
        out = np.empty(2 * n * width, np.uint8)
        wrote = lib.pack_edges(
            _i32p(s), _i32p(d), ctypes.c_int64(n), ctypes.c_int32(width),
            _u8p(out),
        )
        assert wrote == out.nbytes
        # numpy twin: the low `width` little-endian bytes per id, blocks
        twin = np.concatenate(
            [
                np.ascontiguousarray(
                    x.view(np.uint8).reshape(-1, 4)[:, :width]
                ).reshape(-1)
                for x in (s, d)
            ]
        )
        assert np.array_equal(out, twin), f"width {width} pack drift"
    # pair40
    cap = 1 << 20
    s = rng.integers(0, cap, n).astype(np.int32)
    d = rng.integers(0, cap, n).astype(np.int32)
    out = np.empty(5 * n, np.uint8)
    assert lib.pack_edges40(
        _i32p(s), _i32p(d), ctypes.c_int64(n), _u8p(out)
    ) == out.nbytes
    w = (s.astype(np.uint64) & 0xFFFFF) | (
        (d.astype(np.uint64) & 0xFFFFF) << np.uint64(20)
    )
    twin = np.ascontiguousarray(
        w.view(np.uint8).reshape(-1, 8)[:, :5]
    ).reshape(-1)
    assert np.array_equal(out, twin), "pair40 pack drift"
    # BDV encoder over a sorted batch
    order = np.lexsort((s, d))
    s2, d2 = s[order], d[order]
    out = np.empty(wire.bdv_max_nbytes(n) + 8, np.uint8)
    wrote = lib.encode_edges_bdv(
        _i32p(s2), _i32p(d2), ctypes.c_int64(n), _u8p(out),
        ctypes.c_int64(out.nbytes),
    )
    assert wrote > 0
    twin = wire._encode_bdv_np(s2, d2)
    assert np.array_equal(out[:wrote], twin), "BDV encoder drift"


def test_fresh_build_sorter_matches_lexsort(fresh_lib):
    lib = fresh_lib
    lib.sort_edges_dst_src.restype = ctypes.c_int64
    rng = np.random.default_rng(2)
    for cap in (1 << 10, 1 << 23):  # counting-sort and radix regimes
        n = 4096
        s = rng.integers(0, cap, n).astype(np.int32)
        d = rng.integers(0, cap, n).astype(np.int32)
        out_s = np.empty(n, np.int32)
        out_d = np.empty(n, np.int32)
        assert (
            lib.sort_edges_dst_src(
                _i32p(s), _i32p(d), ctypes.c_int64(n), ctypes.c_int32(cap),
                _i32p(out_s), _i32p(out_d),
            )
            == n
        )
        order = np.lexsort((s, d))
        assert np.array_equal(out_s, s[order])
        assert np.array_equal(out_d, d[order])


def test_fresh_build_reader_and_probe_self_check(fresh_lib):
    lib = fresh_lib
    lib.decode_wire_into.restype = ctypes.c_int64
    lib.gly1_probe_prefix.restype = ctypes.c_int32
    lib.gly1_probe_prefix.argtypes = [
        ctypes.c_char_p, ctypes.c_int64, ctypes.c_int64,
        ctypes.POINTER(ctypes.c_int64), ctypes.POINTER(ctypes.c_int64),
    ]
    # probe taxonomy
    hl, pl = ctypes.c_int64(0), ctypes.c_int64(0)
    ok = struct.pack(">4sII", b"GLY1", 7, 9)
    assert lib.gly1_probe_prefix(
        ok, 1 << 16, 1 << 26, ctypes.byref(hl), ctypes.byref(pl)
    ) == 0
    assert (hl.value, pl.value) == (7, 9)
    bad = struct.pack(">4sII", b"XXXX", 7, 9)
    assert lib.gly1_probe_prefix(
        bad, 1 << 16, 1 << 26, ctypes.byref(hl), ctypes.byref(pl)
    ) == -1
    # decode round trips vs the wire twins, every push encoding
    rng = np.random.default_rng(3)
    n = 511
    for cap, width, code in [
        (1 << 14, 2, 2),
        (1 << 19, wire.PAIR40, 5),
        (1 << 22, 3, 3),
        (1 << 26, 4, 4),
    ]:
        s = rng.integers(0, cap, n).astype(np.int32)
        d = rng.integers(0, cap, n).astype(np.int32)
        buf = wire.pack_edges(s, d, width)
        out_s = np.empty(n, np.int32)
        out_d = np.empty(n, np.int32)
        rc = lib.decode_wire_into(
            _u8p(buf), ctypes.c_int64(buf.nbytes), ctypes.c_int64(n),
            ctypes.c_int32(code), ctypes.c_int32(cap), ctypes.c_int32(0),
            _i32p(out_s), _i32p(out_d),
        )
        assert rc == n, (width, rc)
        assert np.array_equal(out_s, s) and np.array_equal(out_d, d)
    # BDV: decode must invert the encoder (sorted multiset) and refuse
    # an id past capacity with the range code
    cap = 1 << 14
    s = rng.integers(0, cap, n).astype(np.int32)
    d = rng.integers(0, cap, n).astype(np.int32)
    buf = wire.pack_edges_bdv(s, d, cap)
    out_s = np.empty(n, np.int32)
    out_d = np.empty(n, np.int32)
    rc = lib.decode_wire_into(
        _u8p(buf), ctypes.c_int64(buf.nbytes), ctypes.c_int64(n),
        ctypes.c_int32(6), ctypes.c_int32(cap), ctypes.c_int32(0),
        _i32p(out_s), _i32p(out_d),
    )
    assert rc == n
    ws, wd = wire.unpack_edges_bdv_host(buf, n)
    assert np.array_equal(out_s, ws) and np.array_equal(out_d, wd)
    rc = lib.decode_wire_into(
        _u8p(buf), ctypes.c_int64(buf.nbytes), ctypes.c_int64(n),
        ctypes.c_int32(6), ctypes.c_int32(8), ctypes.c_int32(0),
        _i32p(out_s), _i32p(out_d),
    )
    assert rc == -2  # id-range refusal


def test_fresh_build_binning_decode_matches_two_pass(fresh_lib):
    """sort=1 (decode + bin in one native pass) equals decode-then-
    sort_edges_binned — the same-pass binning claim, pinned."""
    lib = fresh_lib
    lib.decode_wire_into.restype = ctypes.c_int64
    rng = np.random.default_rng(4)
    cap, n = 1 << 16, 1024
    s = rng.integers(0, cap, n).astype(np.int32)
    d = rng.integers(0, cap, n).astype(np.int32)
    buf = wire.pack_edges(s, d, 2)
    out_s = np.empty(n, np.int32)
    out_d = np.empty(n, np.int32)
    rc = lib.decode_wire_into(
        _u8p(buf), ctypes.c_int64(buf.nbytes), ctypes.c_int64(n),
        ctypes.c_int32(2), ctypes.c_int32(cap), ctypes.c_int32(1),
        _i32p(out_s), _i32p(out_d),
    )
    assert rc == n
    es, ed = wire.sort_edges_binned(s, d, cap)
    assert np.array_equal(out_s, es) and np.array_equal(out_d, ed)

"""Bounded event-time out-of-orderness: watermark trails max-seen time by
``cfg.out_of_orderness_ms``; windows stay open for stragglers inside the
bound; records beyond it route to the late sink (drop by default).

Beyond the reference's ascending-only contract
(SimpleEdgeStream.java:86-90) — the BoundedOutOfOrderness analog of the
Flink watermark assigner the reference sits one call above.
"""

import numpy as np
import pytest

from gelly_streaming_tpu.core.config import StreamConfig
from gelly_streaming_tpu.core.stream import EdgeStream
from gelly_streaming_tpu.core.types import EdgeDirection


def _stream(edges, bound, batch_size=2, **extra):
    cfg = StreamConfig(
        vertex_capacity=16,
        max_degree=16,
        batch_size=batch_size,
        out_of_orderness_ms=bound,
        **extra,
    )
    return EdgeStream.from_collection(
        edges, cfg, batch_size=batch_size, with_time=True
    )


def _reduce_records(stream, window=1000, slide=None):
    out = stream.slice(window, EdgeDirection.OUT, slide_ms=slide).reduce_on_edges(
        lambda a, b: a + b
    )
    return sorted(tuple(r) for r in out.collect())


def test_in_bound_stragglers_join_their_window():
    # the t=800 edge arrives AFTER t=1500 — inside a 1000 ms bound, so
    # window 0 must still be open and include it
    edges = [
        (1, 2, 10, 100),
        (3, 4, 5, 1500),
        (1, 5, 7, 800),  # straggler for window 0
        (2, 3, 9, 2600),
    ]
    got = _reduce_records(_stream(edges, bound=1000))
    # window 0: 1 -> 17; window 1: 3 -> 5; window 2: 2 -> 9
    assert got == [(1, 17), (2, 9), (3, 5)]


def test_beyond_bound_records_are_dropped():
    # with bound=0 (ascending contract) the t=800 record arrives after the
    # watermark passed 1000 -> its window is closed -> dropped
    edges = [
        (1, 2, 10, 100),
        (3, 4, 5, 1500),
        (1, 5, 7, 800),  # late beyond bound
        (2, 3, 9, 2600),
    ]
    got = _reduce_records(_stream(edges, bound=0))
    assert got == [(1, 10), (2, 9), (3, 5)]


def test_late_sink_receives_dropped_records():
    edges = [
        (1, 2, 10, 100),
        (3, 4, 5, 1500),
        (1, 5, 7, 800),
        (2, 3, 9, 2600),
    ]
    lates = []

    def sink(src, dst, val, time):
        lates.extend(zip(src.tolist(), dst.tolist(), time.tolist()))

    got = _reduce_records(_stream(edges, bound=0).on_late(sink))
    assert got == [(1, 10), (2, 9), (3, 5)]
    assert lates == [(1, 5, 800)]


def test_late_sink_survives_transforms():
    edges = [
        (1, 2, 10, 100),
        (3, 4, 5, 1500),
        (1, 5, 7, 800),
        (2, 3, 9, 2600),
    ]
    lates = []
    stream = _stream(edges, bound=0).on_late(
        lambda s, d, v, t: lates.append(len(s))
    )
    _reduce_records(stream.filter_edges(lambda s, d, v: d < 10))
    assert lates == [1]


def test_watermark_holds_windows_open():
    # bound 2000: nothing may close until max_t - 2000 passes a window end;
    # all three windows flush at end-of-stream with stragglers included
    edges = [
        (1, 2, 1, 100),
        (2, 3, 1, 2900),
        (1, 4, 1, 200),  # straggler, still within 2000 of 2900
        (3, 5, 1, 1100),  # straggler for window 1
    ]
    got = _reduce_records(_stream(edges, bound=2000))
    assert got == [(1, 2), (2, 1), (3, 1)]


def test_out_of_order_with_sliding_windows():
    edges = [
        (1, 2, 10, 100),
        (3, 4, 5, 1500),
        (1, 5, 7, 800),  # straggler joins pane 0
        (2, 3, 9, 2600),
    ]
    got = _reduce_records(_stream(edges, bound=1000), window=2000, slide=1000)
    # panes: 0:{(1,17)}, 1:{(3,5)}, 2:{(2,9)}
    # windows (k=2): 0:{p0} 1:{p0,p1} 2:{p1,p2} 3:{p2}
    want = sorted(
        [(1, 17), (1, 17), (3, 5), (3, 5), (2, 9), (2, 9)]
    )
    assert got == want


def test_ascending_streams_unchanged_by_bound_zero():
    edges = [
        (1, 2, 10, 100),
        (3, 1, 7, 900),
        (1, 4, 5, 1500),
        (2, 3, 20, 2400),
    ]
    assert _reduce_records(_stream(edges, bound=0)) == _reduce_records(
        _stream(edges, bound=0, batch_size=4)
    )


def test_negative_bound_rejected():
    with pytest.raises(ValueError, match="out_of_orderness"):
        StreamConfig(vertex_capacity=16, out_of_orderness_ms=-1)


@pytest.mark.parametrize("seed", [0, 1])
def test_out_of_order_differential_vs_sorted(seed):
    """Shuffled-within-bound streams must window identically to the fully
    sorted stream (the bound makes the shuffle invisible)."""
    rng = np.random.default_rng(seed)
    n = 30
    times = np.sort(rng.integers(0, 6000, n))
    edges = [
        (int(rng.integers(1, 8)), int(rng.integers(1, 8)), int(rng.integers(1, 50)), int(t))
        for t in times
    ]
    # bounded shuffle: swap adjacent pairs (displacement <= 1 batch stays
    # well inside a 2000 ms bound for this time density)
    shuffled = list(edges)
    for i in range(0, n - 1, 2):
        shuffled[i], shuffled[i + 1] = shuffled[i + 1], shuffled[i]
    a = _reduce_records(_stream(edges, bound=2000))
    b = _reduce_records(_stream(shuffled, bound=2000))
    assert a == b


def test_on_late_attached_after_derivation_is_seen():
    """on_late on any stream in a chain is visible to all derived streams
    (shared holder), even when attached after the derivation."""
    edges = [(1, 2, 10, 100), (3, 4, 5, 1500), (1, 5, 7, 800)]
    lates = []
    base = _stream(edges, bound=0)
    derived = base.filter_edges(lambda s, d, v: d < 10)
    base.on_late(lambda s, d, v, t: lates.append(len(s)))  # after deriving
    _reduce_records(derived)
    assert lates == [1]


def test_bound_conflicts_with_ingestion_windows():
    with pytest.raises(ValueError, match="event-time"):
        StreamConfig(
            vertex_capacity=16, ingest_window_edges=8, out_of_orderness_ms=100
        )


def test_aggregate_cc_with_out_of_order_stream():
    """The aggregation path shares stream_panes: an out-of-order timed
    stream folds the same components as its sorted equivalent when the
    shuffle stays inside the bound."""
    from gelly_streaming_tpu.library.connected_components import (
        ConnectedComponents,
    )

    sorted_edges = [
        (1, 2, 0, 100),
        (3, 4, 0, 700),
        (2, 3, 0, 1400),
        (5, 6, 0, 2200),
    ]
    shuffled = [sorted_edges[1], sorted_edges[0]] + sorted_edges[2:]

    def components(edges):
        stream = _stream(edges, bound=1000, batch_size=1)
        (ds,) = stream.aggregate(ConnectedComponents(window_ms=1000)).collect()[-1]
        return ds.components()

    got = components(shuffled)
    assert got == components(sorted_edges)
    # and the final summary is the full merge: {1,2,3,4} and {5,6}
    members = sorted(tuple(sorted(v)) for v in got.values())
    assert members == [(1, 2, 3, 4), (5, 6)]


def test_window_fires_at_max_timestamp_boundary():
    """Flink's trigger boundary: a window fires once the watermark reaches
    its maxTimestamp (end - 1), not end.  With bound=0 a record at exactly
    t=999 drives the watermark to window 0's maxTimestamp, so window 0
    closes immediately and a later sub-1000 record is LATE."""
    edges = [
        (1, 2, 10, 100),
        (1, 5, 7, 999),  # watermark -> 999 == maxTimestamp(window 0)
        (3, 4, 5, 500),  # window 0 already fired -> late
        (2, 3, 9, 2600),
    ]
    lates = []
    got = _reduce_records(
        _stream(edges, bound=0).on_late(
            lambda s, d, v, t: lates.extend(zip(s.tolist(), t.tolist()))
        )
    )
    assert got == [(1, 17), (2, 9)]
    assert lates == [(3, 500)]


def test_window_not_late_one_tick_before_boundary():
    """One tick earlier (watermark = maxTimestamp - 1) the window is still
    open and the straggler joins it."""
    edges = [
        (1, 2, 10, 100),
        (1, 5, 7, 998),  # watermark 998 < 999: window 0 still open
        (3, 4, 5, 500),  # joins window 0
        (2, 3, 9, 2600),
    ]
    got = _reduce_records(_stream(edges, bound=0))
    assert got == [(1, 17), (2, 9), (3, 5)]


def test_union_preserves_late_sink_from_inputs():
    """ADVICE round-5 finding: union() used to mint a fresh late holder,
    silently dropping a sink attached to either input chain."""
    # batch_size=1: round-robin arrival order is 100, 1500, 2600, 800 —
    # ascending except the final record, which is late at bound=0
    left_edges = [(1, 2, 10, 100), (2, 3, 9, 2600)]
    right_edges = [(3, 4, 5, 1500), (1, 5, 7, 800)]  # (1,5) late at bound=0
    lates = []
    left = _stream(left_edges, bound=0, batch_size=1)
    right = _stream(right_edges, bound=0, batch_size=1)
    left.on_late(lambda s, d, v, t: lates.extend(zip(s.tolist(), t.tolist())))
    unioned = left.union(right)
    _reduce_records(unioned)
    assert lates == [(1, 800)]


def test_union_late_sink_fans_out_to_both_chains():
    """A sink attached to the UNIONED stream routes late records whichever
    input chain they came from — and is seen when an input chain is
    consumed on its own too."""
    left_edges = [(1, 2, 10, 100), (2, 3, 9, 2600)]
    right_edges = [(3, 4, 5, 1500), (1, 5, 7, 800)]
    lates = []
    left = _stream(left_edges, bound=0, batch_size=1)
    right = _stream(right_edges, bound=0, batch_size=1)
    unioned = left.union(right)
    unioned.on_late(
        lambda s, d, v, t: lates.extend(zip(s.tolist(), t.tolist()))
    )
    _reduce_records(unioned)
    assert lates == [(1, 800)]
    # the fan-out also landed the sink on the input chain itself
    lates.clear()
    _reduce_records(right)
    assert lates == [(1, 800)]


def test_union_sink_attached_to_input_after_union_is_seen():
    left = _stream([(1, 2, 10, 100), (2, 3, 9, 2600)], bound=0, batch_size=1)
    right = _stream([(3, 4, 5, 1500), (1, 5, 7, 800)], bound=0, batch_size=1)
    unioned = left.union(right)
    lates = []
    right.on_late(lambda s, d, v, t: lates.append(len(s)))  # after union()
    _reduce_records(unioned)
    assert lates == [1]

"""Owner-sharded summary state (ISSUE 4): equivalence, recovery, comms.

The sharded plane (core/sharded_state.py) must be BIT-IDENTICAL to the
replicated combine it replaced — cfg.sharded_state=0 keeps the old plane
alive as the in-tree oracle, so every test here runs both and compares
emissions, across the wire streaming fold, event-time windows (incl. late
records and sliding panes), ingestion-time panes, kill-and-resume, and both
library descriptors (CC and the degree summary).  The comms counters and
the retrace guard pin the two quantitative claims: collective bytes stay in
the O(C/S + delta) envelope (never the replicated plane's O(C*S)
full-partial gathers), and the pow2-bucketed capacities keep the compiled
step set closed (0 recompiles across same-bucket panes).
"""

import dataclasses
import os

import numpy as np
import pytest

from gelly_streaming_tpu.core.config import StreamConfig
from gelly_streaming_tpu.core.stream import EdgeStream
from gelly_streaming_tpu.library.connected_components import ConnectedComponents
from gelly_streaming_tpu.library.degree_distribution import (
    DegreeDistributionSummary,
    degree_histogram,
)

CAP = 64
S = 8


def _cfg(**kw):
    base = dict(vertex_capacity=CAP, batch_size=64, num_shards=S, window_ms=1000)
    base.update(kw)
    return StreamConfig(**base)


def _both(cfg):
    return (
        dataclasses.replace(cfg, sharded_state=1),
        dataclasses.replace(cfg, sharded_state=0),
    )


def _rand_edges(n, seed=0, cap=CAP):
    rng = np.random.default_rng(seed)
    return (
        rng.integers(0, cap, n).astype(np.int32),
        rng.integers(0, cap, n).astype(np.int32),
    )


def _timed_edges(n, seed=0, span_ms=3000, cap=CAP):
    rng = np.random.default_rng(seed)
    t = np.sort(rng.integers(0, span_ms, n)).astype(np.int64)
    s, d = _rand_edges(n, seed, cap)
    return [(int(s[i]), int(d[i]), 0.0, int(t[i])) for i in range(n)]


# ---------------------------------------------------------------------------
# emission equivalence: sharded plane == replicated oracle, bit for bit


def test_cc_wire_stream_matches_replicated_oracle():
    src, dst = _rand_edges(500, seed=3)
    on, off = _both(_cfg())
    got = EdgeStream.from_arrays(src, dst, on).aggregate(ConnectedComponents()).collect()
    exp = EdgeStream.from_arrays(src, dst, off).aggregate(ConnectedComponents()).collect()
    assert np.array_equal(np.asarray(got[-1][0].parent), np.asarray(exp[-1][0].parent))
    assert np.array_equal(np.asarray(got[-1][0].seen), np.asarray(exp[-1][0].seen))


def test_cc_wire_replay_with_tail_matches_oracle():
    from gelly_streaming_tpu.io import wire

    src, dst = _rand_edges(500, seed=4)
    width = wire.width_for_capacity(CAP)
    bufs, tail = wire.pack_stream(src, dst, 64, width)
    assert tail is not None
    on, off = _both(_cfg())
    got = (
        EdgeStream.from_wire(bufs, 64, width, on, tail=tail)
        .aggregate(ConnectedComponents())
        .collect()
    )
    exp = (
        EdgeStream.from_wire(bufs, 64, width, off, tail=tail)
        .aggregate(ConnectedComponents())
        .collect()
    )
    assert np.array_equal(np.asarray(got[-1][0].parent), np.asarray(exp[-1][0].parent))


@pytest.mark.parametrize("agg_cls", [ConnectedComponents, DegreeDistributionSummary])
def test_windowed_emissions_match_replicated_oracle(agg_cls):
    edges = _timed_edges(200, seed=5)
    on, off = _both(_cfg(batch_size=16))
    got = [
        o[0]
        for o in EdgeStream.from_collection(edges, on, 16, with_time=True).aggregate(
            agg_cls()
        )
    ]
    exp = [
        o[0]
        for o in EdgeStream.from_collection(edges, off, 16, with_time=True).aggregate(
            agg_cls()
        )
    ]
    assert len(got) == len(exp) >= 3
    for g, e in zip(got, exp):
        ga = np.asarray(g.parent if hasattr(g, "parent") else g)
        ea = np.asarray(e.parent if hasattr(e, "parent") else e)
        assert np.array_equal(ga, ea)


def test_degree_summary_matches_numpy():
    src, dst = _rand_edges(400, seed=6)
    on, _ = _both(_cfg())
    out = (
        EdgeStream.from_arrays(src, dst, on)
        .aggregate(DegreeDistributionSummary())
        .collect()
    )
    expect = np.bincount(src, minlength=CAP) + np.bincount(dst, minlength=CAP)
    assert np.array_equal(np.asarray(out[-1][0]), expect)
    assert degree_histogram(out[-1][0]) == degree_histogram(expect)


def test_late_records_match_replicated_oracle():
    """Bounded out-of-orderness: stragglers within the bound re-open panes
    identically on both planes; later-than-bound records go late on both."""
    edges = _timed_edges(120, seed=7, span_ms=4000)
    # shuffle a straggler window in: move some mid-stream events early
    edges[40] = (edges[40][0], edges[40][1], 0.0, edges[39][3] - 900)
    edges[80] = (edges[80][0], edges[80][1], 0.0, edges[79][3] - 900)
    edges.sort(key=lambda e: e[3])
    # then displace two records to arrive 700ms late relative to arrival order
    late1, late2 = edges.pop(30), edges.pop(60)
    edges.insert(45, (late1[0], late1[1], 0.0, late1[3]))
    edges.append((late2[0], late2[1], 0.0, late2[3]))
    on, off = _both(_cfg(batch_size=8, out_of_orderness_ms=1000))
    got = [
        str(o[0])
        for o in EdgeStream.from_collection(edges, on, 8, with_time=True).aggregate(
            ConnectedComponents()
        )
    ]
    exp = [
        str(o[0])
        for o in EdgeStream.from_collection(edges, off, 8, with_time=True).aggregate(
            ConnectedComponents()
        )
    ]
    assert got == exp


@pytest.mark.parametrize("agg_cls", [ConnectedComponents, DegreeDistributionSummary])
def test_sliding_windows_match_replicated_oracle(agg_cls):
    """Pane-shared sliding windows via the runner's panes override: the
    sharded plane's persistent fold must equal the replicated running merge
    (the combine(a, update(init, e)) == update(a, e) protocol contract)."""
    from gelly_streaming_tpu.core.aggregation import MeshAggregationRunner
    from gelly_streaming_tpu.core.windows import windowed_panes

    edges = _timed_edges(160, seed=8, span_ms=4000)
    on, off = _both(_cfg(batch_size=16))

    def run(cfg):
        stream = EdgeStream.from_collection(edges, cfg, 16, with_time=True)
        agg = agg_cls()
        runner = MeshAggregationRunner(agg)
        return [
            o[0]
            for o in runner.run(
                stream, panes=lambda: windowed_panes(stream, 1000, 500)
            )
        ]

    got, exp = run(on), run(off)
    assert len(got) == len(exp) >= 4
    for g, e in zip(got, exp):
        ga = np.asarray(g.parent if hasattr(g, "parent") else g)
        ea = np.asarray(e.parent if hasattr(e, "parent") else e)
        assert np.array_equal(ga, ea)


def test_ingestion_panes_match_replicated_oracle():
    src, dst = _rand_edges(300, seed=9)
    on, off = _both(_cfg(batch_size=32, ingest_window_edges=64))
    got = [
        str(o[0])
        for o in EdgeStream.from_arrays(src, dst, on).aggregate(ConnectedComponents())
    ]
    exp = [
        str(o[0])
        for o in EdgeStream.from_arrays(src, dst, off).aggregate(ConnectedComponents())
    ]
    assert got == exp and len(got) >= 4


def test_async_windows_match_sync_on_sharded_plane():
    edges = _timed_edges(200, seed=10)
    base = _cfg(batch_size=16, sharded_state=1)
    sync_cfg = dataclasses.replace(base, async_windows=0)
    async_cfg = dataclasses.replace(base, async_windows=3)
    got = [
        str(o[0])
        for o in EdgeStream.from_collection(edges, async_cfg, 16, with_time=True).aggregate(
            ConnectedComponents()
        )
    ]
    exp = [
        str(o[0])
        for o in EdgeStream.from_collection(edges, sync_cfg, 16, with_time=True).aggregate(
            ConnectedComponents()
        )
    ]
    assert got == exp


def test_transient_descriptor_resets_blocks_per_window():
    """transient_state on the sharded plane: blocks reset per pane, so each
    emission covers only its own window — same as the replicated plane."""

    class TransientCC(ConnectedComponents):
        transient_state = True

        @property
        def cache_token(self):
            return type(self)

    edges = _timed_edges(120, seed=11)
    on, off = _both(_cfg(batch_size=16))
    got = [
        str(o[0])
        for o in EdgeStream.from_collection(edges, on, 16, with_time=True).aggregate(
            TransientCC()
        )
    ]
    exp = [
        str(o[0])
        for o in EdgeStream.from_collection(edges, off, 16, with_time=True).aggregate(
            TransientCC()
        )
    ]
    assert got == exp and len(got) >= 2


# ---------------------------------------------------------------------------
# recovery: positional checkpoints + kill-and-resume parity


def test_windowed_kill_and_resume_position_parity(tmp_path):
    """Abandon the sharded windowed plane mid-stream; the resume must skip
    checkpointed windows by position and replay the rest — matching both
    the full sharded run and the replicated oracle's resumed sequence."""
    edges = _timed_edges(160, seed=12)
    on, off = _both(_cfg(batch_size=16))
    full = [
        str(o[0])
        for o in EdgeStream.from_collection(edges, on, 16, with_time=True).aggregate(
            ConnectedComponents()
        )
    ]

    def killed_then_resumed(cfg, ckpt):
        it = iter(
            EdgeStream.from_collection(edges, cfg, 16, with_time=True).aggregate(
                ConnectedComponents(), checkpoint_path=ckpt
            )
        )
        first_two = [str(next(it)[0]), str(next(it)[0])]
        it.close()
        assert os.path.exists(ckpt)
        resumed = [
            str(o[0])
            for o in EdgeStream.from_collection(edges, cfg, 16, with_time=True).aggregate(
                ConnectedComponents(), checkpoint_path=ckpt
            )
        ]
        return first_two, resumed

    first_on, resumed_on = killed_then_resumed(
        on, os.path.join(str(tmp_path), "sharded.npz")
    )
    first_off, resumed_off = killed_then_resumed(
        off, os.path.join(str(tmp_path), "replicated.npz")
    )
    assert first_on == full[:2]
    # window 1's snapshot never landed (generator killed at the yield), so
    # it re-emits: at-least-once, identical on both planes
    assert resumed_on == full[1:]
    assert resumed_on == resumed_off


def test_wire_kill_and_resume_uses_restored_position(tmp_path):
    """Mid-stream wire snapshot: resuming over a POISONED already-folded
    prefix must still reach the full run's summary — proof the restored
    blocks + group position were used instead of re-folding."""
    src, dst = _rand_edges(512, seed=13)
    cfg = _cfg(batch_size=64, wire_checkpoint_batches=8, sharded_state=1)
    ckpt = os.path.join(str(tmp_path), "wire.npz")
    full = (
        EdgeStream.from_arrays(src, dst, cfg)
        .aggregate(ConnectedComponents(), checkpoint_path=ckpt)
        .collect()
    )
    assert os.path.exists(ckpt)
    # done snapshot: resume re-emits (at-least-once) from blocks alone
    again = (
        EdgeStream.from_arrays(src, dst, cfg)
        .aggregate(ConnectedComponents(), checkpoint_path=ckpt)
        .collect()
    )
    assert again[-1][0].components() == full[-1][0].components()

    os.remove(ckpt)
    it = iter(
        EdgeStream.from_arrays(src, dst, cfg).aggregate(
            ConnectedComponents(), checkpoint_path=ckpt
        )
    )
    try:
        next(it)
    except StopIteration:
        pass
    it.close()
    assert os.path.exists(ckpt)
    garbled = src.copy()
    garbled[:256] = 0  # poison the folded prefix
    resumed = (
        EdgeStream.from_arrays(garbled, dst, cfg)
        .aggregate(ConnectedComponents(), checkpoint_path=ckpt)
        .collect()
    )
    assert resumed[-1][0].components() == full[-1][0].components()


def test_wire_checkpoint_geometry_mismatch_raises(tmp_path):
    src, dst = _rand_edges(512, seed=14)
    cfg = _cfg(batch_size=64, wire_checkpoint_batches=8, sharded_state=1)
    ckpt = os.path.join(str(tmp_path), "wire.npz")
    it = iter(
        EdgeStream.from_arrays(src, dst, cfg).aggregate(
            ConnectedComponents(), checkpoint_path=ckpt
        )
    )
    try:
        next(it)
    except StopIteration:
        pass
    it.close()
    assert os.path.exists(ckpt)
    bad = dataclasses.replace(cfg, batch_size=32)
    with pytest.raises(ValueError, match="misalign"):
        EdgeStream.from_arrays(src, dst, bad).aggregate(
            ConnectedComponents(), checkpoint_path=ckpt
        ).collect()


# ---------------------------------------------------------------------------
# comms accounting + the retrace guard


def test_comms_counters_meter_the_sharded_plane():
    from gelly_streaming_tpu.utils import metrics

    src, dst = _rand_edges(400, seed=15)
    on, off = _both(_cfg(batch_size=64))
    metrics.reset_comms_stats()
    EdgeStream.from_arrays(src, dst, on).aggregate(ConnectedComponents()).collect()
    stats = metrics.comms_stats()
    assert stats["comms_dispatches"] > 0
    assert stats["comms_bytes_exchange"] > 0
    assert stats["comms_bytes_gather"] > 0
    assert stats["comms_exchange_rounds"] >= 1
    assert stats["comms_delta_spilled"] == 0
    # the O(C/S + delta) envelope per dispatch, and never the O(C*S) regime
    # of gathering S full partials per shard per dispatch
    c = CAP
    assert stats["comms_bytes_per_dispatch"] <= 8 * (5 * c + 16 * c)
    assert stats["comms_bytes_per_dispatch"] < S * c * 4 * S
    # the replicated oracle plane leaves the counters untouched
    metrics.reset_comms_stats()
    EdgeStream.from_arrays(src, dst, off).aggregate(ConnectedComponents()).collect()
    assert metrics.comms_stats()["comms_dispatches"] == 0


def test_delta_occupancy_tracks_changed_rows_not_capacity():
    """Small panes on a large id space: the measured delta high-water mark
    must scale with the pane's changed rows (the GraphBLAST frontier), not
    with C/S — the claim behind the delta-compressed buffers."""
    from gelly_streaming_tpu.utils import metrics

    big = 1 << 12
    cfg = _cfg(
        vertex_capacity=big, batch_size=16, window_ms=1000, sharded_state=1
    )
    edges = _timed_edges(96, seed=16, span_ms=6000, cap=big)
    metrics.reset_comms_stats()
    EdgeStream.from_collection(edges, cfg, 16, with_time=True).aggregate(
        ConnectedComponents()
    ).collect()
    stats = metrics.comms_stats()
    hwm = stats["comms_delta_occupancy_hwm"]
    assert 0 < hwm <= 2 * 96  # bounded by touched rows...
    assert hwm < big // S  # ...far under the structural C/S ceiling


def test_zero_recompiles_across_same_bucket_panes():
    """Retrace guard (satellite): 50 windows whose occupancy varies inside
    one pow2 capacity bucket reuse ONE compiled sharded step — second run
    of the whole stream compiles nothing and recompiles nothing."""
    from gelly_streaming_tpu.core import compile_cache

    rng = np.random.default_rng(17)
    edges = []
    t = 0
    for w in range(50):
        n = int(rng.integers(33, 65))  # same pow2 bucket at every window
        s, d = _rand_edges(n, seed=100 + w)
        for i in range(n):
            edges.append((int(s[i]), int(d[i]), 0.0, t + i))
        t += 1000
    cfg = _cfg(batch_size=64, sharded_state=1)

    def run(agg_cls):
        return (
            EdgeStream.from_collection(edges, cfg, 64, with_time=True)
            .aggregate(agg_cls())
            .collect()
        )

    # CC rides round-robin pane packing; the degree summary rides the
    # host_route keyBy (route_key="src"), whose auto capacity pow2-buckets —
    # both planes must resolve every same-bucket pane to cached executables
    out1 = run(ConnectedComponents)  # populate the executable cache
    run(DegreeDistributionSummary)
    compile_cache.reset_stats()
    out2 = run(ConnectedComponents)  # re-created streams AND descriptors:
    run(DegreeDistributionSummary)  # everything must hit
    stats = compile_cache.stats()
    assert len(out2) == 50
    assert stats["compiles"] == 0, stats
    assert stats["recompiles"] == 0, stats
    assert str(out1[-1][0]) == str(out2[-1][0])


# ---------------------------------------------------------------------------
# reshard_summary (ISSUE 11): geometry re-route is bit-exact


def _spec_for(agg_cls, cfg):
    return agg_cls().sharded_state_spec(cfg)


def _leaves(tree):
    import jax

    return [np.asarray(leaf) for leaf in jax.tree.leaves(tree)]


def _fold_summary(agg_cls, src, dst, cfg, val=None):
    """The replay oracle: fold the stream fresh on the single-chip plane
    and return the replicated summary pytree the spec's shard_summary
    accepts (descriptor state, not the emitted transform view)."""
    agg = agg_cls()
    import jax.numpy as jnp

    state = agg.initial_state(cfg)
    n = len(src)
    bs = cfg.batch_size
    for i in range(0, max(n, 1), bs):
        s = np.zeros((bs,), np.int32)
        d = np.zeros((bs,), np.int32)
        m = np.zeros((bs,), bool)
        k = len(src[i : i + bs])
        s[:k], d[:k], m[:k] = src[i : i + bs], dst[i : i + bs], True
        v = None
        if val is not None:
            v = np.zeros((bs,), np.float32)
            v[:k] = val[i : i + bs]
            v = jnp.asarray(v)
        if k == 0 and n:
            continue
        state = agg.update(
            state, jnp.asarray(s), jnp.asarray(d), v, jnp.asarray(m)
        )
    return state


@pytest.mark.parametrize(
    "agg_cls", [ConnectedComponents, DegreeDistributionSummary]
)
@pytest.mark.parametrize(
    "shape", ["skewed", "empty", "valued"], ids=["skewed", "empty", "valued"]
)
def test_reshard_round_trip_matches_fresh_shard_oracle(agg_cls, shape):
    """S -> 2S -> S re-routing is bit-identical to sharding the replay
    oracle's summary fresh at each geometry — the contract the elastic
    control plane's state move rests on."""
    from gelly_streaming_tpu.core.sharded_state import reshard_summary

    cfg = _cfg(num_shards=4)
    rng = np.random.default_rng(21)
    if shape == "empty":
        src = dst = np.zeros((0,), np.int32)
        val = None
    elif shape == "valued":
        src, dst = _rand_edges(300, seed=22)
        val = rng.random(300).astype(np.float32)
    else:
        # skew: one hub vertex on most destinations
        src = rng.integers(0, CAP, 400).astype(np.int32)
        dst = np.where(rng.random(400) < 0.7, 3, rng.integers(0, CAP, 400)).astype(np.int32)
        val = None
    summary = _fold_summary(agg_cls, src, dst, cfg, val=val)
    spec = _spec_for(agg_cls, cfg)
    blocks_4 = spec.shard_summary(summary, cfg, 4)
    rerouted_8 = reshard_summary(blocks_4, cfg, 4, 8)
    fresh_8 = spec.shard_summary(summary, cfg, 8)
    for got, exp in zip(_leaves(rerouted_8), _leaves(fresh_8)):
        assert got.shape == exp.shape and got.dtype == exp.dtype
        assert np.array_equal(got, exp)
    # ...and back: the round trip is the identity, bit for bit
    back_4 = reshard_summary(rerouted_8, cfg, 8, 4)
    for got, exp in zip(_leaves(back_4), _leaves(blocks_4)):
        assert np.array_equal(got, exp)


@pytest.mark.parametrize(
    "agg_cls", [ConnectedComponents, DegreeDistributionSummary]
)
def test_reshard_initial_blocks_are_the_new_geometry_identity(agg_cls):
    """Re-routing the fold identity lands exactly on the new geometry's
    own initial blocks — restores and empty shards need no masking at
    either scale."""
    from gelly_streaming_tpu.core.sharded_state import reshard_summary

    cfg = _cfg(num_shards=2)
    spec = _spec_for(agg_cls, cfg)
    rerouted = reshard_summary(spec.initial_shard_state(cfg, 2), cfg, 2, 8)
    fresh = spec.initial_shard_state(cfg, 8)
    for got, exp in zip(_leaves(rerouted), _leaves(fresh)):
        assert np.array_equal(got, exp)


def test_reshard_validates_geometry():
    from gelly_streaming_tpu.core.sharded_state import reshard_summary

    cfg = _cfg(num_shards=4)
    spec = _spec_for(ConnectedComponents, cfg)
    blocks = spec.initial_shard_state(cfg, 4)
    with pytest.raises(ValueError, match="divisible"):
        reshard_summary(blocks, cfg, 4, 3)
    with pytest.raises(ValueError, match="positive"):
        reshard_summary(blocks, cfg, 4, 0)
    with pytest.raises(ValueError, match="owner-block layout"):
        reshard_summary(blocks, cfg, 8, 4)  # leaves are [4, ...], not [8, ...]


def test_sharded_state_env_and_config_resolution(monkeypatch):
    from gelly_streaming_tpu.core.sharded_state import resolve_sharded_state

    assert resolve_sharded_state(_cfg(sharded_state=1))
    assert not resolve_sharded_state(_cfg(sharded_state=0))
    monkeypatch.delenv("GELLY_SHARDED_STATE", raising=False)
    assert resolve_sharded_state(_cfg())  # auto defaults ON
    monkeypatch.setenv("GELLY_SHARDED_STATE", "0")
    assert not resolve_sharded_state(_cfg())
    monkeypatch.setenv("GELLY_SHARDED_STATE", "1")
    assert resolve_sharded_state(_cfg())
    # explicit config wins over the env var
    assert not resolve_sharded_state(_cfg(sharded_state=0))

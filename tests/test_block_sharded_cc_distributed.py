"""Multi-process per-process checkpointing for BlockShardedCC (VERDICT r3
item 5): a 2-process jax.distributed CPU cluster (4 local devices each, 8
mesh shards) runs the block-distributed CC with checkpointing, is KILLED
mid-stream, and resumes from each host's own per-process shard snapshot —
no host ever materializes another host's blocks.  The resumed labels must
equal a host union-find over the full stream even though the resumed run's
replayed prefix is poisoned (proof the restored carry was used)."""

import json
import os
import socket
import subprocess
import sys
import textwrap

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

_WORKER = textwrap.dedent(
    """
    import json, os, sys
    sys.path.insert(0, %(repo)r)
    import jax

    jax.config.update("jax_platforms", "cpu")
    try:
        jax.config.update("jax_num_cpu_devices", 4)
    except AttributeError:  # older jax: pre-init XLA flag instead
        os.environ["XLA_FLAGS"] = (
            os.environ.get("XLA_FLAGS", "")
            + " --xla_force_host_platform_device_count=4"
        ).strip()

    coord, pid, phase, ckpt = (
        sys.argv[1], int(sys.argv[2]), sys.argv[3], sys.argv[4]
    )
    from gelly_streaming_tpu.parallel import multihost as mh

    env = mh.distributed_env(
        coordinator_address=coord, num_processes=2, process_id=pid
    )
    assert len(jax.devices()) == 8, jax.devices()

    import numpy as np

    from gelly_streaming_tpu.core.config import StreamConfig
    from gelly_streaming_tpu.core.stream import EdgeStream
    from gelly_streaming_tpu.core.types import EdgeBatch
    from gelly_streaming_tpu.library.connected_components import (
        BlockShardedCC,
        unshard_labels,
    )

    C = 1 << 10
    rng = np.random.default_rng(11)
    src = rng.integers(0, C, 256).astype(np.int32)
    dst = rng.integers(0, C, 256).astype(np.int32)
    # two ingestion panes of 128 edges each (deterministic arrival cut)
    cfg = StreamConfig(
        vertex_capacity=C, batch_size=64, ingest_window_edges=128
    )
    use_src = src.copy()
    if phase == "resume":
        # poison the already-folded prefix: only the restored snapshot can
        # still produce the right labels
        use_src[:128] = 0

    def batches():
        for i in range(0, 256, 64):
            yield EdgeBatch.from_arrays(use_src[i:i+64], dst[i:i+64])

    cc = BlockShardedCC()
    out = cc.run(
        EdgeStream.from_batches(batches, cfg), checkpoint_path=ckpt
    )
    it = iter(out)
    first = next(it)  # pane 0 folded (snapshot runs when the gen resumes)
    if phase == "crash":
        next(it)  # resuming past the yield writes pane 0's snapshot
        proc_file = ckpt[:-4] + f".proc{pid}.npz"
        assert os.path.exists(proc_file), proc_file
        print("RESULT " + json.dumps({"crashed_after": 1}), flush=True)
        sys.exit(0)  # "crash": no further panes folded
    rest = list(it)
    final = rest[-1][0] if rest else first[0]
    from jax.experimental import multihost_utils

    full = multihost_utils.process_allgather(final, tiled=True)
    labels = unshard_labels(full)
    print("RESULT " + json.dumps({"labels": labels.tolist()}), flush=True)
    """
)


def _run_pair(tmp_path, phase, ckpt):
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
    coord = f"127.0.0.1:{port}"
    env = dict(os.environ)
    env.pop("XLA_FLAGS", None)
    procs, logs = [], []
    for pid in (0, 1):
        out_f = open(tmp_path / f"{phase}{pid}.out", "w+")
        err_f = open(tmp_path / f"{phase}{pid}.err", "w+")
        logs.append((out_f, err_f))
        procs.append(
            subprocess.Popen(
                [
                    sys.executable, "-c", _WORKER % {"repo": REPO},
                    coord, str(pid), phase, ckpt,
                ],
                stdout=out_f, stderr=err_f, env=env, text=True,
            )
        )
    outs = []
    try:
        for p in procs:
            p.wait(timeout=240)
    except subprocess.TimeoutExpired:
        for q in procs:
            q.kill()
        raise
    for p, (out_f, err_f) in zip(procs, logs):
        out_f.seek(0)
        err_f.seek(0)
        stdout, stderr = out_f.read(), err_f.read()
        out_f.close()
        err_f.close()
        if "Multiprocess computations aren't implemented" in stderr:
            import pytest

            pytest.skip(
                "this jax build's CPU backend has no multi-process "
                "collectives (jax.distributed over CPU unsupported)"
            )
        assert p.returncode == 0, stderr[-3000:]
        line = [l for l in stdout.splitlines() if l.startswith("RESULT ")][-1]
        outs.append(json.loads(line[len("RESULT "):]))
    return outs


def test_block_sharded_cc_multiprocess_kill_and_resume(tmp_path):
    import numpy as np

    ckpt = str(tmp_path / "blockcc.npz")
    crash = _run_pair(tmp_path, "crash", ckpt)
    assert all(o == {"crashed_after": 1} for o in crash)
    base = ckpt[:-4]
    assert os.path.exists(base + ".proc0.npz")
    assert os.path.exists(base + ".proc1.npz")

    resumed = _run_pair(tmp_path, "resume", ckpt)
    labels = np.array(resumed[0]["labels"])
    assert resumed[1]["labels"] == resumed[0]["labels"]

    # host union-find over the TRUE full stream (the resume run's replayed
    # prefix was poisoned, so matching labels prove the snapshot was used)
    C = 1 << 10
    rng = np.random.default_rng(11)
    src = rng.integers(0, C, 256).astype(np.int64)
    dst = rng.integers(0, C, 256).astype(np.int64)
    parent = np.arange(C)

    def find(v):
        while parent[v] != v:
            parent[v] = parent[parent[v]]
            v = parent[v]
        return v

    for a, b in zip(src, dst):
        ra, rb = find(int(a)), find(int(b))
        if ra != rb:
            parent[max(ra, rb)] = min(ra, rb)
    expect = np.array([find(v) for v in range(C)])
    assert np.array_equal(labels, expect)

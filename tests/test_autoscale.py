"""Elastic control plane (ISSUE 11): health-driven live re-sharding.

The contracts under test:

* POLICY — deterministic, injected-clock walks of the decision rules:
  sustained PAGE scales up by the factor, sustained over-provisioned-idle
  scales down, cooldown spaces decisions, a failing actuator journals
  ``scale_failed`` and cools down instead of retrying at tick rate,
  terminal jobs retire their registration and scale gauges.
* ACTUATION — a served push job drained and resubmitted at 2x the shard
  geometry resumes bit-exactly from its checkpoint cursor: emissions
  across the rescale are overlap-only, the non-idempotent degree counts
  are exact (every edge folded exactly once into persistent state), and
  mid-swap pushes are refused ``quiesced``/typed so the client re-pushes
  from the cursor.
* FAULT INJECTION — the acceptance walk: a deliberately lagging job
  (1-record results buffer nobody drains) pages its backlog-age SLO, the
  autoscaler drains + resubmits it at 2x, the alert walks back down
  through the normal hysteretic path once a consumer appears, and the
  ENTIRE decision chain (both job incarnations + scale events) replays
  from the JSONL journal.
* OFF BY DEFAULT — with ``RuntimeConfig.autoscale`` unset and no
  ``GELLY_AUTOSCALE``, no policy thread exists and emissions/recompiles
  are bit-identical to a run with the control plane enabled but
  untriggered.

Every threaded test carries ``timeout_cap``.
"""

import dataclasses
import threading
import time

import numpy as np
import pytest

from gelly_streaming_tpu.core.config import (
    AutoscalePolicy,
    RuntimeConfig,
    ServerConfig,
    SLOSpec,
    StreamConfig,
)
from gelly_streaming_tpu.runtime import JobManager
from gelly_streaming_tpu.runtime.autoscale import (
    Autoscaler,
    resolve_autoscale,
)
from gelly_streaming_tpu.runtime.client import (
    GellyClient,
    ServerRefused,
)
from gelly_streaming_tpu.runtime.server import (
    StreamServer,
    _ServedRescaleTarget,
)
from gelly_streaming_tpu.utils import events, metrics

pytestmark = pytest.mark.timeout_cap(300)

CAP = 1 << 12
W = 1 << 10
B = 1 << 9


def _graph(seed: int, n: int, cap: int = CAP):
    rng = np.random.default_rng(seed)
    return (
        rng.integers(0, cap, n).astype(np.int32),
        rng.integers(0, cap, n).astype(np.int32),
    )


def _reset_registries():
    metrics.reset_alerts()
    metrics.reset_job_health()
    metrics.reset_job_scale()
    metrics.reset_histograms()
    events.configure(path=None)


class FakeHandle:
    """A scripted RescaleTarget for the deterministic policy walks."""

    def __init__(self, shards: int = 1, state: str = "RUNNING", fail=False):
        self.shards = shards
        self.state = state
        self.fail = fail
        self.calls = []

    def job_state(self):
        return self.state

    def current_shards(self):
        return self.shards

    def eligible(self, num_shards):
        return 1 <= num_shards <= 8

    def rescale(self, num_shards, reason):
        self.calls.append((num_shards, reason))
        if self.fail:
            raise RuntimeError("injected actuation failure")
        self.shards = num_shards
        return {"resume_edges": num_shards * 1024}


# ---------------------------------------------------------------------------
# config + switch resolution
# ---------------------------------------------------------------------------


def test_autoscale_policy_validation():
    with pytest.raises(ValueError, match="factor"):
        AutoscalePolicy(factor=1)
    with pytest.raises(ValueError, match="page_hold"):
        AutoscalePolicy(page_hold=0)
    with pytest.raises(ValueError, match="idle_keepup"):
        AutoscalePolicy(idle_keepup=1.0)
    with pytest.raises(ValueError, match="max_shards"):
        AutoscalePolicy(min_shards=4, max_shards=2)
    with pytest.raises(ValueError, match="interval_s"):
        AutoscalePolicy(interval_s=0)
    with pytest.raises(ValueError, match="autoscale must be"):
        RuntimeConfig(autoscale=7)
    with pytest.raises(ValueError, match="AutoscalePolicy"):
        RuntimeConfig(autoscale_policy={"factor": 2})


def test_resolve_autoscale_config_and_env(monkeypatch):
    monkeypatch.delenv("GELLY_AUTOSCALE", raising=False)
    assert not resolve_autoscale(RuntimeConfig())  # default OFF
    assert resolve_autoscale(RuntimeConfig(autoscale=1))
    assert not resolve_autoscale(RuntimeConfig(autoscale=0))
    monkeypatch.setenv("GELLY_AUTOSCALE", "1")
    assert resolve_autoscale(RuntimeConfig())
    assert not resolve_autoscale(RuntimeConfig(autoscale=0))  # config wins
    monkeypatch.setenv("GELLY_AUTOSCALE", "maybe")
    with pytest.raises(ValueError, match="GELLY_AUTOSCALE"):
        resolve_autoscale(RuntimeConfig())


def test_manager_starts_no_autoscaler_by_default(monkeypatch):
    monkeypatch.delenv("GELLY_AUTOSCALE", raising=False)
    with JobManager() as jm:
        job = jm.submit(lambda: iter(()), name="plain")
        job.collect()
        assert jm.autoscaler is None
    with JobManager(RuntimeConfig(autoscale=1)) as jm:
        job = jm.submit(lambda: iter(()), name="managed")
        job.collect()
        assert jm.autoscaler is not None
        assert jm.autoscaler.stats()["running"]


# ---------------------------------------------------------------------------
# deterministic policy walks (injected clocks, scripted handles)
# ---------------------------------------------------------------------------


def _policy(**kw):
    base = dict(page_hold=2, idle_hold=3, idle_keepup=4.0, cooldown_s=10.0)
    base.update(kw)
    return AutoscalePolicy(**base)


def test_sustained_page_scales_up_and_cooldown_spaces_decisions():
    _reset_registries()
    journal = events.EventJournal(clock=lambda: 0.0)
    h = FakeHandle()
    a = Autoscaler(_policy(), clock=lambda: 0.0, journal=journal)
    a.register("t/j", h)
    metrics.alert_set("job", "t/j", "lag", {"state": "PAGE", "burn_fast": 9.0})
    assert a.evaluate_once(0.0) == []  # streak 1 < page_hold
    out = a.evaluate_once(1.0)  # streak 2 -> decide + actuate
    assert len(out) == 1 and out[0]["ok"]
    assert out[0]["direction"] == "up" and out[0]["new_shards"] == 2
    assert out[0]["trigger"] == 9.0
    assert h.calls == [(2, "page-burn")]
    row = metrics.job_scale("t/j")
    assert row["actual_shards"] == row["desired_shards"] == 2
    assert row["rescales"] == 1 and row["last_reason"] == "page-burn"
    # cooldown: still paging, but no decision until the quiet period ends
    assert a.evaluate_once(2.0) == [] and a.evaluate_once(3.0) == []
    assert h.shards == 2
    # past cooldown the still-burning job doubles again (its streak kept
    # accumulating through the quiet period)
    out = a.evaluate_once(12.0)
    assert out and out[0]["new_shards"] == 4 and h.shards == 4
    kinds = [e["kind"] for e in journal.tail(100)]
    assert kinds.count("scale_decision") == kinds.count("scale_done") == 2


def test_sustained_idle_scales_down():
    _reset_registries()
    h = FakeHandle(shards=4)
    a = Autoscaler(_policy(cooldown_s=0.0), clock=lambda: 0.0)
    a.register("t/j", h)
    metrics.job_health_set(
        "t/j",
        {"keepup_ratio": 9.0, "backlog_batches": 0, "watermark_lag_windows": 0},
    )
    outs = [a.evaluate_once(float(t)) for t in range(3)]
    assert outs[0] == [] and outs[1] == []
    assert outs[2] and outs[2][0]["direction"] == "down"
    assert outs[2][0]["reason"] == "idle" and h.shards == 2
    # a burning alert vetoes the idle verdict even with a huge keep-up
    metrics.alert_set("job", "t/j", "lag", {"state": "WARN"})
    for t in range(3, 9):
        assert a.evaluate_once(float(t)) == []
    assert h.shards == 2


def test_idle_needs_empty_backlog_and_min_shards_floor():
    _reset_registries()
    h = FakeHandle(shards=2)
    a = Autoscaler(_policy(idle_hold=1, cooldown_s=0.0), clock=lambda: 0.0)
    a.register("t/j", h)
    # backlog present: over-provisioned by rate but still holding bytes
    metrics.job_health_set(
        "t/j",
        {"keepup_ratio": 9.0, "backlog_batches": 3, "watermark_lag_windows": 0},
    )
    assert a.evaluate_once(0.0) == []
    metrics.job_health_set(
        "t/j",
        {"keepup_ratio": 9.0, "backlog_batches": 0, "watermark_lag_windows": 0},
    )
    assert a.evaluate_once(1.0)[0]["new_shards"] == 1
    # at the floor: idle forever, no decision
    for t in range(2, 6):
        assert a.evaluate_once(float(t)) == []
    assert h.shards == 1


def test_failed_actuation_journals_scale_failed_and_cools_down():
    _reset_registries()
    journal = events.EventJournal(clock=lambda: 0.0)
    h = FakeHandle(fail=True)
    a = Autoscaler(_policy(page_hold=1), clock=lambda: 0.0, journal=journal)
    a.register("t/j", h)
    metrics.alert_set("job", "t/j", "lag", {"state": "PAGE"})
    out = a.evaluate_once(0.0)
    assert out and not out[0]["ok"] and "injected" in out[0]["error"]
    assert a.stats()["failures"] == 1
    failed = journal.tail(10, kind="scale_failed")
    assert failed and failed[0]["old_shards"] == 1
    row = metrics.job_scale("t/j")
    # desired snaps back: the gauge must not advertise a geometry nobody
    # is moving toward
    assert row["desired_shards"] == row["actual_shards"] == 1
    assert row["last_reason"] == "failed:page-burn"
    # cooldown: the failing actuator is NOT retried at tick rate
    assert a.evaluate_once(1.0) == [] and len(h.calls) == 1


def test_terminal_job_retires_registration_and_scale_row():
    _reset_registries()
    h = FakeHandle()
    a = Autoscaler(_policy(), clock=lambda: 0.0)
    a.register("t/j", h)
    assert metrics.job_scale("t/j")["actual_shards"] == 1
    h.state = "DONE"
    a.evaluate_once(0.0)
    assert a.managed() == []
    assert metrics.job_scale("t/j") == {}


def test_broken_handle_degrades_not_kills_the_sweep():
    _reset_registries()

    class Broken(FakeHandle):
        def __init__(self):
            super().__init__()
            self._armed = False  # registration's gauge seed still works

        def current_shards(self):
            if self._armed:
                raise RuntimeError("probe died mid-life")
            self._armed = True
            return self.shards

    good = FakeHandle()
    a = Autoscaler(_policy(page_hold=1), clock=lambda: 0.0)
    a.register("a/bad", Broken())
    a.register("b/good", good)
    metrics.alert_set("job", "b/good", "lag", {"state": "PAGE"})
    out = a.evaluate_once(0.0)
    assert [d["job"] for d in out] == ["b/good"] and good.shards == 2


# ---------------------------------------------------------------------------
# gelly-top SCALE surfacing
# ---------------------------------------------------------------------------


def test_top_frame_carries_scale_rows():
    from gelly_streaming_tpu.runtime.top import frame_dict, render_frame

    status = {
        "server": {"connections": 1, "served_jobs": 1, "port": 7},
        "status": {"jobs": {"t/j": {"state": "RUNNING", "job_edges": 10}}},
    }
    snap = {
        "tenants": {},
        "pipeline": {},
        "scale": {
            "t/j": {
                "actual_shards": 2,
                "desired_shards": 4,
                "last_reason": "page-burn",
            }
        },
    }
    frame = frame_dict(status, snap, None, None)
    assert frame["scale"]["t/j"]["desired_shards"] == 4
    import json

    json.dumps(frame)
    lines = render_frame(status, snap, None, None)
    assert any("SCALE" in line for line in lines)
    assert any("2->4 page-burn" in line for line in lines)
    # an unmanaged job renders "-"
    snap2 = dict(snap, scale={})
    assert any(
        line.rstrip().endswith("-") for line in render_frame(status, snap2, None, None)
    )


# ---------------------------------------------------------------------------
# journal helpers: incarnation history
# ---------------------------------------------------------------------------


def test_job_history_reconstructs_both_incarnations():
    j = events.EventJournal()
    j.emit("job_submitted", job="t/x")
    for frm, to in (("PENDING", "RUNNING"), ("RUNNING", "CANCELLED")):
        j.emit("job_transition", job="t/x", **{"from": frm, "to": to})
    j.emit("scale_decision", job="t/x", old_shards=1, new_shards=2)
    j.emit("scale_done", job="t/x", old_shards=1, new_shards=2)
    j.emit("job_submitted", job="t/x")
    for frm, to in (
        ("PENDING", "RUNNING"),
        ("RUNNING", "DRAINING"),
        ("DRAINING", "DONE"),
    ):
        j.emit("job_transition", job="t/x", **{"from": frm, "to": to})
    evs = j.tail(100)
    assert events.job_history(evs, "t/x") == [
        ["PENDING", "RUNNING", "CANCELLED"],
        ["PENDING", "RUNNING", "DRAINING", "DONE"],
    ]
    # job_lifecycle keeps returning the LATEST incarnation
    assert events.job_lifecycle(evs, "t/x") == [
        "PENDING",
        "RUNNING",
        "DRAINING",
        "DONE",
    ]
    # the scale records sit between the incarnations in seq order
    seqs = {e["kind"]: e["seq"] for e in evs}
    cancel_seq = max(
        e["seq"] for e in evs if e.get("to") == "CANCELLED"
    )
    resubmit_seq = max(
        e["seq"] for e in evs if e["kind"] == "job_submitted"
    )
    assert cancel_seq < seqs["scale_decision"] < seqs["scale_done"] < resubmit_seq


# ---------------------------------------------------------------------------
# served-job actuation: drain -> 2x geometry -> bit-exact resume
# ---------------------------------------------------------------------------


def _window_id(deg_record: np.ndarray) -> int:
    """Infer a degree record's window id: sum(deg) == 2 * edges folded ==
    2 * (window + 1) * W (every edge adds one to each endpoint)."""
    total = int(deg_record.sum())
    assert total % (2 * W) == 0, total
    return total // (2 * W) - 1


def _assert_overlap_only(records, src, dst, n_windows, resume_w):
    """Every record bit-matches its window's fresh-fold oracle prefix;
    coverage is complete; duplicates only in the checkpoint-to-drain
    overlap region starting at the resume cursor (at-least-once)."""
    seen: dict = {}
    for rec in records:
        deg = np.asarray(rec[0])
        k = _window_id(deg)
        edges = (k + 1) * W
        oracle = np.bincount(src[:edges], minlength=CAP) + np.bincount(
            dst[:edges], minlength=CAP
        )
        assert np.array_equal(deg, oracle.astype(deg.dtype)), f"window {k}"
        seen[k] = seen.get(k, 0) + 1
    assert set(seen) == set(range(n_windows)), sorted(seen)
    dups = sorted(k for k, c in seen.items() if c > 1)
    assert all(c <= 2 for c in seen.values())
    # overlap-only: re-emitted windows are exactly a contiguous run from
    # the resume cursor (emitted pre-drain past the last landed snapshot)
    assert dups == list(range(resume_w, resume_w + len(dups))), (
        dups,
        resume_w,
    )


def test_served_rescale_resumes_bit_exact_at_2x(tmp_path):
    _reset_registries()
    n_windows = 16
    n = n_windows * W
    s, d = _graph(41, n)
    rt = RuntimeConfig(health_sample_s=0.0)
    with JobManager(rt) as jm, StreamServer(
        jm, ServerConfig(checkpoint_prefix=str(tmp_path / "ck"))
    ) as server:
        with GellyClient("127.0.0.1", server.port) as c:
            reply = c.submit(
                name="dj",
                query="degree",
                capacity=CAP,
                window_edges=W,
                batch=B,
                checkpoint=True,
            )
            assert reply["resume_edges"] == 0
            head = 8 * W
            c.push_edges("dj", s[:head], d[:head], batch=B, capacity=CAP, close=False)
            records = []
            while len(records) < 4:  # let several windows fold + checkpoint
                recs, state, _eos = c.results("dj", timeout_ms=2000)
                records.extend(recs)
                assert state not in ("FAILED", "CANCELLED")
            with server._lock:
                sj = server._jobs["default/dj"]
            handle = _ServedRescaleTarget(server, sj)
            assert handle.current_shards() == 1
            assert handle.eligible(2) and not handle.eligible(3)
            res = handle.rescale(2, "test")
            resume = res["resume_edges"]
            assert 0 < resume <= head and resume % W == 0
            assert sj.cfg.num_shards == 2 and sj.job.state != "CANCELLED"
            # a push against the OLD pre-swap position is impossible now;
            # the client re-pushes everything from the cursor
            c.push_edges(
                "dj", s, d, batch=B, capacity=CAP, start=resume, close=True
            )
            for rec in c.iter_results("dj", deadline_s=240):
                records.append(rec)
            _assert_overlap_only(records, s, d, n_windows, resume // W)
            # the swap re-priced, never double-booked: exactly one job's
            # state bytes admitted, nothing stuck in the reservation
            status = jm.status()
            assert status["reserved_state_bytes"] == 0
            assert (
                status["admitted_state_bytes"] == 0
            )  # job DONE: budget returned
    _reset_registries()


def test_mid_swap_push_is_refused_quiesced_then_client_recovers(tmp_path):
    """Pushes racing the swap get the typed ``quiesced`` refusal (their
    batches are the client's to re-push from the cursor) — the pipelined
    push drain surfaces it as ServerRefused without desyncing the
    connection, and the SAME connection then completes the stream."""
    _reset_registries()
    n_windows = 12
    n = n_windows * W
    s, d = _graph(43, n)
    with JobManager() as jm, StreamServer(
        jm, ServerConfig(checkpoint_prefix=str(tmp_path / "ck"))
    ) as server:
        with GellyClient("127.0.0.1", server.port) as c:
            c.submit(
                name="rj",
                query="degree",
                capacity=CAP,
                window_edges=W,
                batch=B,
                checkpoint=True,
            )
            with server._lock:
                sj = server._jobs["default/rj"]
            handle = _ServedRescaleTarget(server, sj)
            stop = threading.Event()
            errors = []

            def pusher():
                start = 0
                while not stop.is_set() and start < n:
                    try:
                        c.push_edges(
                            "rj",
                            s[: start + 2 * W],
                            d[: start + 2 * W],
                            batch=B,
                            capacity=CAP,
                            start=start,
                            close=False,
                        )
                        start += 2 * W
                    except ServerRefused as e:
                        if e.code not in ("quiesced", "out-of-sync"):
                            errors.append(e)
                            return
                        # the rescale contract: a quiesced refusal (the
                        # swap in progress) or a positionally-stale frame
                        # landing after it both mean the same thing —
                        # stop, then re-push from the NEW cursor
                        time.sleep(0.05)
                        return

            th = threading.Thread(target=pusher)
            th.start()
            time.sleep(0.1)  # let some pushes land
            res = handle.rescale(2, "test")
            stop.set()
            th.join(60)
            assert not errors, errors
            resume = res["resume_edges"]
            # the same connection finishes the stream from the cursor
            c.push_edges(
                "rj", s, d, batch=B, capacity=CAP, start=resume, close=True
            )
            records = list(c.iter_results("rj", deadline_s=240))
            final = np.asarray(records[-1][0])
            oracle = np.bincount(s, minlength=CAP) + np.bincount(
                d, minlength=CAP
            )
            assert np.array_equal(final, oracle.astype(final.dtype))
    _reset_registries()


def test_tenant_caps_hold_across_the_rescale_swap_window(tmp_path):
    """Mid-swap, the draining job reads terminal/zero-byte, so the
    per-tenant cap arithmetic would see a vacancy — the tenant-swap
    figures must keep both the byte and the job cap charged until the
    resubmit lands (the manager-level reservation's guarantee, applied
    one layer up)."""
    _reset_registries()
    from gelly_streaming_tpu.core.config import TenantConfig
    from gelly_streaming_tpu.library.degree_distribution import (
        DegreeDistributionSummary,
    )

    cfg = StreamConfig(
        vertex_capacity=CAP, batch_size=B, ingest_window_edges=W
    )
    one_job = DegreeDistributionSummary().state_nbytes(cfg)
    srv_cfg = ServerConfig(
        tenants=(
            TenantConfig(
                tenant="t",
                token="tok",
                max_jobs=2,
                max_state_bytes=one_job,
            ),
        ),
        checkpoint_prefix=str(tmp_path / "ck"),
    )
    with JobManager() as jm, StreamServer(jm, srv_cfg) as server:
        with GellyClient("127.0.0.1", server.port, token="tok") as c:
            c.submit(
                name="scaling",
                query="degree",
                capacity=CAP,
                window_edges=W,
                batch=B,
                checkpoint=True,
            )
            with server._lock:
                sj = server._jobs["t/scaling"]
            # open the swap window exactly as _rescale_served does, then
            # drain the old job to its mid-swap terminal/zero-byte state
            with server._admission:
                reserved = jm.begin_rescale(sj.job, one_job)
                server._tenant_swap_begin("t", one_job)
            sj.source.quiesce()
            jm.cancel(sj.job, wait=True)
            assert sj.job.state_bytes == 0  # the vacancy a thief would see
            # the tenant's byte cap still reads FULL: a concurrent
            # same-tenant submit is refused, not admitted into the gap
            with pytest.raises(ServerRefused) as ei:
                c.submit(
                    name="thief",
                    query="degree",
                    capacity=CAP,
                    window_edges=W,
                    batch=B,
                )
            assert ei.value.code == "admission"
            assert "state-byte cap" in str(ei.value)
            # close the window; the budget frees and the tenant can
            # submit again
            jm.abort_rescale(reserved)
            server._tenant_swap_end("t", one_job)
            c.submit(
                name="after",
                query="degree",
                capacity=CAP,
                window_edges=W,
                batch=B,
            )
    _reset_registries()


def test_tenant_job_cap_counts_inflight_swaps(tmp_path):
    _reset_registries()
    from gelly_streaming_tpu.core.config import TenantConfig

    srv_cfg = ServerConfig(
        tenants=(TenantConfig(tenant="t", token="tok", max_jobs=1),),
        checkpoint_prefix=str(tmp_path / "ck"),
    )
    with JobManager() as jm, StreamServer(jm, srv_cfg) as server:
        with GellyClient("127.0.0.1", server.port, token="tok") as c:
            c.submit(
                name="scaling",
                query="degree",
                capacity=CAP,
                window_edges=W,
                batch=B,
                checkpoint=True,
            )
            with server._lock:
                sj = server._jobs["t/scaling"]
            server._tenant_swap_begin("t", 0)
            sj.source.quiesce()
            jm.cancel(sj.job, wait=True)  # live jobs now 0, swaps 1
            with pytest.raises(ServerRefused, match="job cap"):
                c.submit(
                    name="thief",
                    query="degree",
                    capacity=CAP,
                    window_edges=W,
                    batch=B,
                )
            server._tenant_swap_end("t", 0)
            c.submit(
                name="after",
                query="degree",
                capacity=CAP,
                window_edges=W,
                batch=B,
            )
    _reset_registries()


def test_push_offset_guard_refuses_positionally_stale_frames(tmp_path):
    """The positional wire guard: a push declaring an offset that is not
    the source's accepted-edge count is refused ``out-of-sync`` (the
    stale-pipelined-frame-after-a-swap hole), the connection survives,
    and correctly-offset pushes proceed.  Undeclared offsets keep the
    legacy no-check behavior."""
    _reset_registries()
    from gelly_streaming_tpu.core.config import StreamConfig
    from gelly_streaming_tpu.io.sources import (
        NetworkEdgeSource,
        PushOutOfSync,
    )

    cfg = StreamConfig(
        vertex_capacity=CAP, batch_size=B, ingest_window_edges=W
    )
    s, d = _graph(59, B)
    # unit: the source's own check, resume filler included
    src = NetworkEdgeSource(cfg, B, resume_edges=2 * W, max_queued_batches=4)
    with pytest.raises(PushOutOfSync, match="re-push from"):
        src.push_tail(s, d, offset=0)  # the pre-rescale stream's position
    assert src.push_tail(s, d, offset=2 * W) == B  # cursor-exact: accepted
    assert src.push_tail(s, d) == B  # no declaration: legacy behavior
    # end to end: the server maps it to the typed out-of-sync refusal and
    # the SAME connection recovers with the right offset
    with JobManager() as jm, StreamServer(jm, ServerConfig()) as server:
        with GellyClient("127.0.0.1", server.port) as c:
            c.submit(
                name="oj", query="degree", capacity=CAP, window_edges=W, batch=B
            )
            with pytest.raises(ServerRefused) as ei:
                c.push_tail("oj", s, d, offset=5 * B)
            assert ei.value.code == "out-of-sync"
            # incremental multi-call pushes: each call ships a fresh
            # chunk; 'position' declares the chunk's global offset (and
            # declare_position=False keeps the legacy unchecked behavior)
            n = 4 * W
            s2, d2 = _graph(61, n)
            half = n // 2
            c.push_edges(
                "oj", s2[:half], d2[:half], batch=B, capacity=CAP,
                close=False,
            )
            c.push_edges(
                "oj", s2[half : half + W], d2[half : half + W], batch=B,
                capacity=CAP, close=False, position=half,
            )
            c.push_edges(
                "oj", s2[half + W :], d2[half + W :], batch=B, capacity=CAP,
                declare_position=False,
            )
            records = list(c.iter_results("oj", deadline_s=240))
            final = np.asarray(records[-1][0])
            oracle = np.bincount(s2, minlength=CAP) + np.bincount(
                d2, minlength=CAP
            )
            assert np.array_equal(final, oracle.astype(final.dtype))
    _reset_registries()


def test_resume_pushes_reopens_a_quiesced_source():
    """The rescale failure path's client story: a drain that never
    completed reopens the source, so pushes flow again instead of being
    refused quiesced forever."""
    from gelly_streaming_tpu.core.config import StreamConfig
    from gelly_streaming_tpu.io.sources import (
        NetworkEdgeSource,
        SourceQuiesced,
    )

    cfg = StreamConfig(
        vertex_capacity=CAP, batch_size=B, ingest_window_edges=W
    )
    s, d = _graph(67, B)
    src = NetworkEdgeSource(cfg, B, max_queued_batches=4)
    src.quiesce()
    assert src.draining
    with pytest.raises(SourceQuiesced):
        src.push_tail(s, d)
    src.resume_pushes()
    assert not src.draining
    assert src.push_tail(s, d) == B
    # a CLOSED source stays closed: resume_pushes is for drains only
    src.close()
    src.resume_pushes()
    with pytest.raises(SourceQuiesced, match="closed"):
        src.push_tail(s, d)


# ---------------------------------------------------------------------------
# the acceptance walk: injected lag -> PAGE -> autoscale 2x -> hysteretic
# clear -> exact counts -> full chain replayable from the JSONL journal
# ---------------------------------------------------------------------------


def test_fault_injection_paged_job_autoscales_and_clears(tmp_path):
    metrics.reset_alerts()
    metrics.reset_job_health()
    metrics.reset_job_scale()
    metrics.reset_histograms()
    journal_path = str(tmp_path / "events.jsonl")
    events.configure(path=journal_path)
    try:
        spec = SLOSpec(
            metric="max_backlog_age_s",
            threshold=0.15,
            error_budget=0.5,
            fast_window_s=0.4,
            slow_window_s=1.0,
            warn_burn=1.0,
            page_burn=1.5,
            clear_hold=2,
        )
        policy = AutoscalePolicy(
            page_hold=2,
            idle_hold=10_000,  # this walk exercises scale-UP only
            cooldown_s=300.0,  # one decision per test
            interval_s=0.05,
        )
        rt = RuntimeConfig(
            health_sample_s=0.03,
            slos=(spec,),
            slo_interval_s=0.25,
            job_queue_depth=2,
            autoscale=1,
            autoscale_policy=policy,
        )
        n_windows = 24
        n = n_windows * W
        s, d = _graph(47, n)
        with JobManager(rt) as jm, StreamServer(
            jm,
            ServerConfig(
                result_buffer_records=1,
                checkpoint_prefix=str(tmp_path / "ck"),
            ),
        ) as server:
            with GellyClient("127.0.0.1", server.port) as c:
                c.submit(
                    name="hj",
                    query="degree",
                    capacity=CAP,
                    window_edges=W,
                    batch=B,
                    checkpoint=True,
                )
                assert jm.autoscaler is not None
                assert "default/hj" in jm.autoscaler.managed()
                # inject lag: push the whole stream with nobody fetching
                # results (1-record buffer + depth-2 queue = the scheduler
                # wedges after ~3 windows; the backlog AGES).  The rescale
                # may quiesce mid-push — that typed refusal is part of the
                # contract under test.
                try:
                    c.push_edges(
                        "hj", s, d, batch=B, capacity=CAP, close=False
                    )
                except ServerRefused as e:
                    # quiesced = the swap caught the push mid-flight;
                    # out-of-sync = a pipelined frame landed after it
                    assert e.code in ("quiesced", "out-of-sync")

                def wait_for(pred, what, deadline_s=120):
                    deadline = time.monotonic() + deadline_s
                    while time.monotonic() < deadline:
                        if pred():
                            return
                        time.sleep(0.02)
                    raise AssertionError(f"never observed: {what}")

                # the autoscaler rescales the paged job to 2 shards
                wait_for(
                    lambda: metrics.job_scale("default/hj").get(
                        "actual_shards"
                    )
                    == 2,
                    "scale row at 2 shards",
                )
                done = events.journal().tail(50, kind="scale_done")
                assert done and done[-1]["job"] == "default/hj"
                assert done[-1]["old_shards"] == 1
                assert done[-1]["new_shards"] == 2
                assert done[-1]["reason"] == "page-burn"
                assert done[-1]["downtime_ms"] >= 0
                resume = int(done[-1]["resume_edges"])
                assert resume % W == 0
                # the PAGE that drove it is on the record
                decisions = events.journal().tail(50, kind="scale_decision")
                assert decisions[-1]["direction"] == "up"
                # gelly-client events (the client API the CLI prints)
                # shows the scale records, tenant-scoped
                assert any(
                    e["kind"] == "scale_done" for e in c.events(200)
                )
                scale_row = c.metrics()["scale"]["default/hj"]
                assert scale_row["actual_shards"] == 2
                assert scale_row["last_reason"] == "page-burn"

                # recovery: re-push from the cursor (retrying while the
                # swap settles) and consume everything
                deadline = time.monotonic() + 120
                while True:
                    try:
                        c.push_edges(
                            "hj",
                            s,
                            d,
                            batch=B,
                            capacity=CAP,
                            start=resume,
                            close=True,
                        )
                        break
                    except ServerRefused as e:
                        assert e.code in ("quiesced", "out-of-sync")
                        assert time.monotonic() < deadline
                        time.sleep(0.05)
                records = []
                for rec in c.iter_results("hj", deadline_s=240):
                    records.append(rec)
                # exact non-idempotent counts: at-least-once emissions,
                # exactly-once state, overlap only past the cursor
                _assert_overlap_only(records, s, d, n_windows, resume // W)
                # the SLO alert clears through the normal path: any
                # recorded transition is a single hysteretic step, and
                # the alert ends at OK
                wait_for(
                    lambda: (
                        metrics.alert_state(
                            "job", "default/hj", "max_backlog_age_s"
                        )
                        or {"state": "OK"}
                    )["state"]
                    == "OK",
                    "alert cleared to OK",
                )
                alert_seq = [
                    (e["from"], e["to"])
                    for e in events.journal().tail(400, kind="alert")
                    if e.get("id") == "default/hj"
                ]
                # the walk started at OK and reached PAGE (escalation may
                # jump straight there when both windows exceed the page
                # burn on one eval — that immediacy is by design); every
                # DE-escalation is a single hysteretic step down
                assert alert_seq and alert_seq[0][0] == "OK"
                assert any(to == "PAGE" for _f, to in alert_seq)
                for frm, to in alert_seq:
                    if metrics.ALERT_LEVELS[to] < metrics.ALERT_LEVELS[frm]:
                        assert (
                            metrics.ALERT_LEVELS[frm]
                            - metrics.ALERT_LEVELS[to]
                            == 1
                        )
            assert jm.wait_all(120)
        # the FULL decision chain replays from the JSONL file: first
        # incarnation drains to CANCELLED, the scale records bridge, the
        # second incarnation runs to DONE
        replayed = events.replay(journal_path)
        history = events.job_history(replayed, "default/hj")
        assert len(history) == 2
        assert history[0][:2] == ["PENDING", "RUNNING"]
        assert history[0][-1] == "CANCELLED"
        assert history[1][0] == "PENDING" and history[1][-1] == "DONE"
        kinds = [e["kind"] for e in replayed]
        assert "scale_decision" in kinds and "scale_done" in kinds
        dec_seq = next(
            e["seq"] for e in replayed if e["kind"] == "scale_decision"
        )
        cancel_seq = next(
            e["seq"]
            for e in replayed
            if e["kind"] == "job_transition" and e.get("to") == "CANCELLED"
        )
        resubmit_seq = max(
            e["seq"] for e in replayed if e["kind"] == "job_submitted"
        )
        assert cancel_seq < resubmit_seq
        assert dec_seq < resubmit_seq
        # torn-tail behavior unchanged: a crash mid-write past the scale
        # records still replays everything before it
        with open(journal_path, "a") as f:
            f.write('{"seq": 999999, "kind": "scale_de')
        assert len(events.replay(journal_path)) == len(replayed)
    finally:
        events.configure(path=None)
        _reset_registries()


# ---------------------------------------------------------------------------
# off-by-default invariant: bit-identical emissions, zero recompiles
# ---------------------------------------------------------------------------


def test_autoscale_off_is_bit_identical_with_zero_recompiles(monkeypatch):
    monkeypatch.delenv("GELLY_AUTOSCALE", raising=False)
    _reset_registries()
    cfg = StreamConfig(
        vertex_capacity=CAP, batch_size=B, ingest_window_edges=W
    )
    from gelly_streaming_tpu.core.stream import EdgeStream
    from gelly_streaming_tpu.library.degree_distribution import (
        DegreeDistributionSummary,
    )

    s, d = _graph(53, 8 * W)

    def run(rt_cfg):
        with JobManager(rt_cfg) as jm:
            job = jm.submit_aggregation(
                EdgeStream.from_arrays(s, d, cfg),
                DegreeDistributionSummary(),
                name="inv",
            )
            return [np.asarray(rec[0]) for rec in job.results()]

    off = run(RuntimeConfig())  # the default: no policy thread at all
    metrics.reset_compile_cache_stats()
    # enabled but never triggered (no SLOs -> nothing ever pages; no
    # registered handles -> nothing to actuate): the control plane must
    # be pure observation
    on = run(
        RuntimeConfig(
            autoscale=1,
            autoscale_policy=AutoscalePolicy(interval_s=0.01),
            health_sample_s=0.01,
        )
    )
    assert metrics.compile_cache_stats()["recompiles"] == 0
    assert len(off) == len(on)
    for a, b in zip(off, on):
        assert np.array_equal(a, b)
    _reset_registries()

"""Pallas MXU triangle kernel: exactness against a dense numpy reference.

On CPU (the test mesh) the kernel runs in Pallas interpret mode — same program
the TPU compiles, executed by the interpreter — so these tests validate the
kernel logic itself, not just a fallback path.
"""

import numpy as np
import pytest

from gelly_streaming_tpu.ops import pallas_triangles


def _dense_reference(adj: np.ndarray) -> int:
    a = adj.astype(np.int64)
    return int(np.sum(a * (a @ a)) // 6)


@pytest.mark.parametrize(
    "n,p,seed", [(30, 0.3, 0), (128, 0.1, 1), (200, 0.05, 2), (257, 0.2, 3)]
)
def test_matches_dense_reference(n, p, seed):
    rng = np.random.default_rng(seed)
    upper = np.triu(rng.random((n, n)) < p, 1)
    adj = upper | upper.T
    u, v = np.nonzero(upper)
    got = pallas_triangles.pane_triangles_dense(
        u.astype(np.int32), v.astype(np.int32), n
    )
    assert got == _dense_reference(adj)


def test_empty_and_triangle_free():
    assert pallas_triangles.pane_triangles_dense(
        np.array([], np.int32), np.array([], np.int32), 0
    ) == 0
    # a path graph has no triangles
    u = np.arange(10, dtype=np.int32)
    v = u + 1
    assert pallas_triangles.pane_triangles_dense(u, v, 11) == 0


def test_single_triangle_and_k4():
    u = np.array([0, 0, 1], np.int32)
    v = np.array([1, 2, 2], np.int32)
    assert pallas_triangles.pane_triangles_dense(u, v, 3) == 1
    # K4 has 4 triangles
    uu, vv = zip(*[(a, b) for a in range(4) for b in range(a + 1, 4)])
    assert pallas_triangles.pane_triangles_dense(
        np.array(uu, np.int32), np.array(vv, np.int32), 4
    ) == 4


def test_rejects_unpadded_shapes():
    import jax.numpy as jnp

    with pytest.raises(ValueError):
        pallas_triangles.triangle_count_dense(
            jnp.zeros((100, 100), jnp.bfloat16), interpret=True
        )


def test_pack_pane_rejects_oversized_ids():
    """pack_pane packs u into the low id bits — an id >= 2^_ID_BITS would
    silently bleed into v (advisor r3 low); it must raise instead."""
    import numpy as np
    import pytest

    from gelly_streaming_tpu.ops.pallas_triangles import _ID_BITS, pack_pane

    ok_u = np.array([1, 2], np.int32)
    ok_v = np.array([3, (1 << _ID_BITS) - 1], np.int32)
    w, n = pack_pane(ok_u, ok_v)
    assert int(n) == 2
    with pytest.raises(ValueError, match="pack_pane ids"):
        pack_pane(np.array([1 << _ID_BITS], np.int32), np.array([0], np.int32))
    with pytest.raises(ValueError, match="pack_pane ids"):
        pack_pane(np.array([-1], np.int32), np.array([0], np.int32))

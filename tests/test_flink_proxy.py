"""flink_proxy_cc: the measured Flink-shaped record-at-a-time baseline.

The bench's ``flink_proxy_eps`` denominator (native/edge_parser.cpp) pays
the reference's real per-record costs — Tuple2 serialization, a kernel
socketpair shuffle hop, HashMap DisjointSet state (pom.xml:38-63,
SimpleEdgeStream.java:461-478, DisjointSet.java:92-118).  These tests pin
its correctness contract: it must process every record exactly once and
produce the identical min-root labels as the array union-find baseline.
"""

import ctypes

import numpy as np
import pytest

from gelly_streaming_tpu.utils.native import load_ingest_lib


@pytest.fixture(scope="module")
def lib():
    lib = load_ingest_lib()
    if lib is None or not hasattr(lib, "flink_proxy_cc"):
        pytest.skip("native ingest lib unavailable")
    return lib


def _i32p(a):
    return a.ctypes.data_as(ctypes.POINTER(ctypes.c_int32))


def test_proxy_labels_match_cc_baseline(lib):
    rng = np.random.default_rng(3)
    n, cap = 200_000, 1 << 16
    src = rng.integers(0, cap, n, dtype=np.int32)
    dst = rng.integers(0, cap, n, dtype=np.int32)
    labels = np.empty(cap, np.int32)
    ns = lib.flink_proxy_cc(_i32p(src), _i32p(dst), n, _i32p(labels), cap)
    assert ns > 0, "proxy must consume every record (rc=-1 on a short read)"
    parent = np.empty(cap, np.int32)
    lib.cc_baseline(_i32p(src), _i32p(dst), n, _i32p(parent), cap)
    assert np.array_equal(labels, parent)


def test_proxy_untouched_vertices_keep_own_label(lib):
    cap = 1024
    src = np.array([1, 2], np.int32)
    dst = np.array([2, 3], np.int32)
    labels = np.empty(cap, np.int32)
    ns = lib.flink_proxy_cc(_i32p(src), _i32p(dst), 2, _i32p(labels), cap)
    assert ns > 0
    assert labels[1] == labels[2] == labels[3] == 1
    untouched = np.concatenate([[0], np.arange(4, cap)])
    assert np.array_equal(labels[untouched], untouched)


def test_proxy_empty_stream(lib):
    cap = 64
    src = np.empty(0, np.int32)
    dst = np.empty(0, np.int32)
    labels = np.empty(cap, np.int32)
    ns = lib.flink_proxy_cc(_i32p(src), _i32p(dst), 0, _i32p(labels), cap)
    assert ns >= 0
    assert np.array_equal(labels, np.arange(cap, dtype=np.int32))

"""Iterative (label-propagation) connected components tests
(IterativeConnectedComponents.java semantics, feedback loop replaced by the
on-device fixed point)."""

import numpy as np

from gelly_streaming_tpu.core.config import StreamConfig
from gelly_streaming_tpu.core.stream import EdgeStream
from gelly_streaming_tpu.library.iterative_cc import IterativeConnectedComponents

CFG = StreamConfig(vertex_capacity=16, max_degree=16)


def test_labels_converge_to_min_component_id():
    edges = [(1, 2), (3, 4), (2, 3), (6, 7)]
    algo = IterativeConnectedComponents()
    recs = algo.run(EdgeStream.from_collection(edges, CFG, batch_size=1)).collect()
    last = {}
    for v, c in recs:
        last[v] = c
    assert last == {1: 1, 2: 1, 3: 1, 4: 1, 6: 6, 7: 6}
    labels = algo.final_labels
    assert labels[4] == 1 and labels[7] == 6


def test_merge_reemits_relabeled_vertices():
    # (3,4) forms component 3; bridging edge (2,3) relabels 3 and 4 to 1's
    # component -> both must be re-emitted with the new label.
    edges = [(1, 2), (3, 4), (2, 3)]
    algo = IterativeConnectedComponents()
    recs = algo.run(EdgeStream.from_collection(edges, CFG, batch_size=1)).collect()
    assert (3, 3) in recs and (3, 1) in recs and (4, 1) in recs

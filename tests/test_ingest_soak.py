"""Unbounded ingestion-time soak: bounded memory, steady cadence, kill-resume.

The reference's flagship UX is an example that runs FOREVER under an
unbounded source with running per-window emission
(ConnectedComponentsExample.java:65-67).  The round-4 tests proved a few
panes of that mode; this module soaks it (VERDICT r4 item 8): >= 10^4
ingestion-time panes through the product ``aggregate()`` path with

  * RSS growth bounded (a PaneAssembler that retained pane arrays would leak
    ~8 KiB x panes — an order of magnitude past the asserted bound),
  * steady emission cadence (late panes no slower than early panes beyond a
    contention tolerance), and
  * a real mid-stream SIGKILL + resume with ``ingest_window_edges``
    checkpointing, proven exactly-once by a non-idempotent fold.
"""

import os
import signal
import subprocess
import sys
import textwrap
import time

import numpy as np
import pytest

from gelly_streaming_tpu.core.config import StreamConfig
from gelly_streaming_tpu.library.connected_components import ConnectedComponents

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

PANES = int(os.environ.get("GELLY_SOAK_PANES", 10_000))
PANE_EDGES = 1024


def _rss_bytes() -> int:
    with open("/proc/self/statm") as f:
        return int(f.read().split()[1]) * os.sysconf("SC_PAGE_SIZE")


def test_unbounded_ingest_soak_bounded_memory_and_cadence():
    from gelly_streaming_tpu.io.sources import unbounded_generated_stream

    cfg = StreamConfig(
        vertex_capacity=1 << 10,
        batch_size=PANE_EDGES,
        ingest_window_edges=PANE_EDGES,
    )
    stream = unbounded_generated_stream(
        cfg, num_vertices=1 << 10, max_batches=None
    )
    out = iter(stream.aggregate(ConnectedComponents()))

    warmup = max(PANES // 10, 100)
    t_early = t_late = None
    rss_base = None
    window = max(PANES // 10, 100)  # cadence probe width
    t0 = None
    for i in range(PANES):
        next(out)
        if i == warmup:
            rss_base = _rss_bytes()
            t0 = time.perf_counter()
        elif i == warmup + window:
            t_early = time.perf_counter() - t0
        elif i == PANES - window:
            t0 = time.perf_counter()
        elif i == PANES - 1:
            t_late = time.perf_counter() - t0
    rss_end = _rss_bytes()
    out.close()

    growth = rss_end - rss_base
    # a retained-pane leak costs >= 2 x PANE_EDGES x 4 B per pane
    # (~8 KiB x ~9k panes ~= 74 MB); normal growth (jit caches, allocator
    # slack) stays in the single-digit MBs
    assert growth < 48 << 20, (
        f"RSS grew {growth >> 20} MB over {PANES - warmup} panes — "
        "pane state is accumulating"
    )
    # steady cadence: the same pane count late in the stream must not take
    # disproportionately longer than early (3x absorbs CI contention; a
    # per-pane cost growing with pane INDEX — e.g. an emission list being
    # rescanned — would blow past it over a 10x span)
    assert t_late < 3.0 * t_early, (
        f"emission cadence degraded: first {window} panes {t_early:.2f}s, "
        f"last {window} panes {t_late:.2f}s"
    )


_CHILD = textwrap.dedent(
    """
    import os, signal, sys
    os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=1"
    sys.path.insert(0, {repo!r})
    import jax
    jax.config.update("jax_platforms", "cpu")
    import numpy as np
    import jax.numpy as jnp
    from gelly_streaming_tpu.core.aggregation import SummaryBulkAggregation
    from gelly_streaming_tpu.core.config import StreamConfig
    from gelly_streaming_tpu.core.stream import EdgeStream

    class EdgeCount(SummaryBulkAggregation):
        # NON-idempotent: refolding any pane after resume overcounts, a
        # dropped pane undercounts — the final value proves exactly-once
        def initial_state(self, cfg):
            return jnp.zeros((), jnp.int32)

        def update(self, state, src, dst, val, mask):
            return state + jnp.sum(mask.astype(jnp.int32))

        def combine(self, a, b):
            return a + b

    kill_after = int(os.environ.get("KILL_AFTER_SAVES", "0"))
    if kill_after:
        import gelly_streaming_tpu.utils.checkpoint as ckpt
        real = ckpt.save_state
        n = [0]
        def hooked(p, s):
            real(p, s)
            n[0] += 1
            if n[0] >= kill_after:
                os.kill(os.getpid(), signal.SIGKILL)
        ckpt.save_state = hooked

    rng = np.random.default_rng(11)
    src = rng.integers(0, 128, 4096).astype(np.int32)
    dst = rng.integers(0, 128, 4096).astype(np.int32)
    cfg = StreamConfig(
        vertex_capacity=128, batch_size=64, ingest_window_edges=96
    )
    out = (
        EdgeStream.from_arrays(src, dst, cfg)
        .aggregate(EdgeCount(), checkpoint_path={ckpt_path!r})
        .collect()
    )
    print("FINAL_COUNT", int(out[-1][0]))
    print("PANES", len(out))
    """
)


@pytest.mark.timeout_cap(600)
def test_unbounded_ingest_sigkill_resume_subprocess(tmp_path):
    """SIGKILL mid-stream while folding ingestion-time panes, resume from the
    on-disk snapshot: the non-idempotent edge count comes out exact."""
    ckpt_path = str(tmp_path / "ingest_ck")
    script = tmp_path / "child.py"
    script.write_text(_CHILD.format(repo=REPO, ckpt_path=ckpt_path))

    env = dict(os.environ, KILL_AFTER_SAVES="3")
    first = subprocess.run(
        [sys.executable, str(script)], env=env, capture_output=True,
        timeout=300,
    )
    assert first.returncode == -signal.SIGKILL, (
        first.returncode, first.stdout, first.stderr,
    )
    assert os.path.exists(ckpt_path + ".npz"), "snapshot must survive the kill"

    env.pop("KILL_AFTER_SAVES")
    second = subprocess.run(
        [sys.executable, str(script)], env=env, capture_output=True,
        timeout=300,
    )
    assert second.returncode == 0, second.stderr.decode()
    assert b"FINAL_COUNT 4096" in second.stdout, second.stdout

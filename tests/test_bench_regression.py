"""bench.py --check-regression (ISSUE 10 satellite): fresh bench JSON vs
the best-so-far per key across the recorded ``BENCH_r*.json`` artifacts.

The properties pinned: direction-aware verdicts (eps regress downward,
latency/recompiles upward), the configurable tolerance, the absolute
guard for a 0 lower-better best (recompiles creeping off zero), and the
``_PARTIAL`` safety contract — keys missing from a partial fresh run or
from every baseline are SKIP/NEW, never failures, and a torn baseline
artifact is ignored rather than fatal.
"""

import json
import os
import sys

import pytest

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import bench  # noqa: E402  (repo-root module; no jax at import time)


def _write(tmp_path, name, doc):
    path = tmp_path / name
    path.write_text(json.dumps(doc))
    return str(path)


@pytest.fixture
def baselines(tmp_path):
    # one driver-wrapper artifact, one raw bench line, one torn file
    _write(
        tmp_path,
        "BENCH_r01.json",
        {
            "n": 1,
            "parsed": {
                "value": 100e6,
                "e2e_eps": 5e6,
                "cache_recompiles": 0,
                "wire_bytes_per_edge": 2.7,
                "triangle_p50_ms": 40.0,
                "edges": 1 << 20,  # untracked: no direction rule
            },
        },
    )
    _write(
        tmp_path,
        "BENCH_r02.json",
        {"value": 120e6, "e2e_eps": 4e6, "triangle_p50_ms": 55.0},
    )
    (tmp_path / "BENCH_r03.json").write_text('{"torn')
    return tmp_path


def _check(tmp_path, fresh_doc, tolerance=0.05):
    fresh = _write(tmp_path, "fresh.json", fresh_doc)
    return bench.check_regression(
        fresh, str(tmp_path / "BENCH_r*.json"), tolerance
    )


def test_direction_rules():
    assert bench._bench_direction("value") == "higher"
    assert bench._bench_direction("e2e_eps") == "higher"
    assert bench._bench_direction("async_window_speedup") == "higher"
    assert bench._bench_direction("wire_compress_ratio") == "higher"
    assert bench._bench_direction("triangle_p50_ms") == "lower"
    assert bench._bench_direction("wire_bytes_per_edge") == "lower"
    assert bench._bench_direction("cache_recompiles") == "lower"
    assert bench._bench_direction("pipeline_pack_stall_s") == "lower"
    # the rescale sub-bench's keys (ISSUE 11): downtime regresses upward,
    # the throughput figures downward
    assert bench._bench_direction("rescale_downtime_ms") == "lower"
    assert bench._bench_direction("rescale_post_eps_ratio") == "higher"
    assert bench._bench_direction("rescale_pre_eps") == "higher"
    assert bench._bench_direction("rescale_post_eps") == "higher"
    assert bench._bench_direction("rescale_resume_edges") is None
    assert bench._bench_direction("edges") is None
    assert bench._bench_direction("link_regime") is None
    # the serving data plane's first-class keys (ISSUE 14): the
    # server-vs-in-process ratio regresses downward (the 0.4 -> 0.8 climb
    # is pinned), push-to-fold latency upward; the decode-pool shape
    # figures are informational only
    assert bench._bench_direction("serving_vs_inprocess_ratio") == "higher"
    assert bench._bench_direction("serving_vs_inprocess_ratio_4") == "higher"
    assert bench._bench_direction("serving_push_to_fold_p50_ms") == "lower"
    assert bench._bench_direction("serving_push_to_fold_p99_ms") == "lower"
    assert bench._bench_direction("serving_decode_workers") is None
    assert bench._bench_direction("serving_decode_native") is None
    # the fused-dispatch headlines (ISSUE 16): aggregate eps at 16 jobs,
    # the fused-vs-solo speedup, scheduler fairness, and bit-exact parity
    # all regress downward; the retrace guard upward (recompiles rule);
    # cohort-shape figures are informational only
    assert bench._bench_direction("fused_agg_eps_16") == "higher"
    assert bench._bench_direction("fused_vs_solo_speedup") == "higher"
    assert bench._bench_direction("fairness_min_max_fused") == "higher"
    assert bench._bench_direction("fused_parity_ok") == "higher"
    assert bench._bench_direction("fused_recompiles_after_warm") == "lower"
    assert bench._bench_direction("fused_compiles_after_warm") is None
    assert bench._bench_direction("fused_jobs_per_dispatch_hwm") is None
    assert bench._bench_direction("fused_jobs_per_dispatch_mean") is None
    assert bench._bench_direction("fused_solo_fallbacks") is None
    # the spmv kernel-core headlines (ISSUE 17): the direction-optimization
    # speedup, pagerank throughput, and cross-direction answer parity all
    # regress downward; the retrace guard upward; the registry counters
    # (iteration split, density histogram, switches) are informational
    assert bench._bench_direction("spmv_direction_speedup") == "higher"
    assert bench._bench_direction("spmv_pagerank_eps") == "higher"
    assert bench._bench_direction("spmv_parity_ok") == "higher"
    assert bench._bench_direction("spmv_recompiles_after_warm") == "lower"
    assert bench._bench_direction("spmv_push_iters") is None
    assert bench._bench_direction("spmv_density_hist_0") is None
    assert bench._bench_direction("spmv_direction_switches") is None
    # the sketch-summary headlines (ISSUE 19): the tenancy ratio and the
    # sketch aggregate eps regress downward, the triangle relative error
    # and the retrace guard upward; raw admission counts and the exact
    # triangle figure are informational
    assert bench._bench_direction("sketch_tenancy_ratio") == "higher"
    assert bench._bench_direction("sketch_agg_eps_16") == "higher"
    assert bench._bench_direction("sketch_triangle_rel_err") == "lower"
    assert bench._bench_direction("sketch_recompiles_after_warm") == "lower"
    assert bench._bench_direction("sketch_compiles_after_warm") is None
    assert bench._bench_direction("sketch_admitted") is None
    assert bench._bench_direction("sketch_exact_admitted") is None
    assert bench._bench_direction("sketch_triangle_exact") is None
    # the fleet-tier headlines (ISSUE 20): aggregate eps at each backend
    # count and the 4-vs-1 scaling ratio regress downward; the router's
    # placed-verb tax, the failover downtime, and the behind-the-router
    # retrace guard upward
    assert bench._bench_direction("fleet_agg_eps_1") == "higher"
    assert bench._bench_direction("fleet_agg_eps_2") == "higher"
    assert bench._bench_direction("fleet_agg_eps_4") == "higher"
    assert bench._bench_direction("fleet_scaling_ratio") == "higher"
    assert bench._bench_direction("router_overhead_p50_ms") == "lower"
    assert bench._bench_direction("fleet_failover_downtime_ms") == "lower"
    assert bench._bench_direction("fleet_warm_recompiles") == "lower"


def test_fresh_at_best_passes(baselines, capsys):
    rc = _check(
        baselines,
        {
            "value": 118e6,  # within 5% of the 120e6 best
            "e2e_eps": 5.2e6,
            "cache_recompiles": 0,
            "wire_bytes_per_edge": 2.69,
            "triangle_p50_ms": 41.0,
        },
    )
    out = capsys.readouterr().out
    assert rc == 0
    assert "REGRESS" not in out
    assert "0 regression(s)" in out


def test_higher_better_regression_fails(baselines, capsys):
    rc = _check(baselines, {"value": 90e6})
    out = capsys.readouterr().out
    assert rc == 1
    assert "value" in out and "REGRESS" in out


def test_lower_better_regression_fails(baselines, capsys):
    rc = _check(baselines, {"value": 125e6, "triangle_p50_ms": 70.0})
    assert rc == 1
    assert "triangle_p50_ms" in capsys.readouterr().out


def test_zero_baseline_recompiles_guarded_absolutely(baselines):
    # best cache_recompiles is 0: a fresh run at 2 is a regression even
    # though 2 > 0 * (1 + tol) would otherwise never trip
    assert _check(baselines, {"cache_recompiles": 2}) == 1
    assert _check(baselines, {"cache_recompiles": 0}) == 0


def test_partial_fresh_skips_never_fails(baselines, capsys):
    # a device_unavailable partial carries only host-side keys
    rc = _check(baselines, {"cpu_baseline_eps": 9e7, "device_unavailable": True})
    out = capsys.readouterr().out
    assert rc == 0
    assert "SKIP" in out and "NEW" in out  # cpu_baseline_eps has no baseline


def test_tolerance_is_configurable(baselines):
    assert _check(baselines, {"value": 100e6}, tolerance=0.05) == 1
    assert _check(baselines, {"value": 100e6}, tolerance=0.2) == 0


def test_untracked_and_nonscalar_keys_ignored(baselines, capsys):
    rc = _check(
        baselines,
        {"edges": 1, "chunks": [1, 2], "link_regime": "healthy"},
    )
    out = capsys.readouterr().out
    assert rc == 0
    assert "edges" not in out.split()  # not a tracked row

"""Wire-format tests: pack/unpack roundtrips, native/numpy agreement, prefetch.

The wire format (io/wire.py) is the host->device serialization boundary — the
analog of the reference's Flink/Netty record serialization, which is covered
there by the runtime, not the library.  Here it is in-repo code, so it gets
direct tests: exact roundtrips at every width, byte-identical native vs numpy
packing, ordered prefetching, and error propagation.
"""

import numpy as np
import pytest

from gelly_streaming_tpu.io import wire
from gelly_streaming_tpu.utils.native import load_ingest_lib


def _random_edges(n, capacity, seed=0):
    rng = np.random.default_rng(seed)
    return (
        rng.integers(0, capacity, n).astype(np.int32),
        rng.integers(0, capacity, n).astype(np.int32),
    )


def test_width_for_capacity_boundaries():
    assert wire.width_for_capacity(1 << 16) == 2
    assert wire.width_for_capacity((1 << 16) + 1) == wire.PAIR40
    assert wire.width_for_capacity(1 << 20) == wire.PAIR40
    assert wire.width_for_capacity((1 << 20) + 1) == 3
    assert wire.width_for_capacity(1 << 24) == 3
    assert wire.width_for_capacity((1 << 24) + 1) == 4


def test_pair40_roundtrip_and_size():
    import jax.numpy as jnp

    src, dst = _random_edges(513, 1 << 20, seed=11)
    buf = wire.pack_edges(src, dst, wire.PAIR40)
    assert buf.shape == (5 * 513,)  # 5 bytes per edge
    s, d = wire.unpack_edges(jnp.asarray(buf), 513, wire.PAIR40)
    np.testing.assert_array_equal(np.asarray(s), src)
    np.testing.assert_array_equal(np.asarray(d), dst)


def test_pair40_native_matches_numpy(monkeypatch):
    lib = load_ingest_lib()
    if lib is None or not hasattr(lib, "pack_edges40"):
        pytest.skip("native pack_edges40 unavailable")
    src, dst = _random_edges(1000, 1 << 20, seed=12)
    native_buf = wire.pack_edges(src, dst, wire.PAIR40)
    monkeypatch.setattr(wire, "load_ingest_lib", lambda: None)
    fallback_buf = wire.pack_edges(src, dst, wire.PAIR40)
    np.testing.assert_array_equal(native_buf, fallback_buf)


@pytest.mark.parametrize("width", [2, 3, 4])
def test_pack_unpack_roundtrip(width):
    import jax.numpy as jnp

    capacity = 1 << (8 * width - 1)  # exercise the high bit of the top byte
    src, dst = _random_edges(257, capacity, seed=width)
    buf = wire.pack_edges(src, dst, width)
    assert buf.dtype == np.uint8 and buf.shape == (2 * 257 * width,)
    s, d = wire.unpack_edges(jnp.asarray(buf), 257, width)
    np.testing.assert_array_equal(np.asarray(s), src)
    np.testing.assert_array_equal(np.asarray(d), dst)


@pytest.mark.parametrize("width", [2, 3, 4])
def test_native_matches_numpy_fallback(width, monkeypatch):
    lib = load_ingest_lib()
    if lib is None:
        pytest.skip("native library unavailable")
    src, dst = _random_edges(1000, 1 << (8 * width - 1), seed=7)
    native_buf = wire.pack_edges(src, dst, width)

    # run the module's own numpy fallback branch by hiding the native lib
    monkeypatch.setattr(wire, "load_ingest_lib", lambda: None)
    fallback_buf = wire.pack_edges(src, dst, width)
    np.testing.assert_array_equal(native_buf, fallback_buf)


def test_pack_rejects_bad_width_and_mismatch():
    src, dst = _random_edges(8, 100)
    with pytest.raises(ValueError):
        wire.pack_edges(src, dst, 5)
    with pytest.raises(ValueError):
        wire.pack_edges(src, dst[:4], 3)


def test_unpack_fuses_into_union_fold():
    """The bench path: unpack inside a jitted union-find fold is exact."""
    import jax
    import jax.numpy as jnp

    from gelly_streaming_tpu.ops import unionfind as uf

    capacity, batch = 1 << 10, 64
    src, dst = _random_edges(batch, capacity, seed=3)

    def fold(parent, seen, buf):
        s, d = wire.unpack_edges(buf, batch, 2)
        return uf.union_edges_with_seen(parent, seen, s, d, None)

    parent = uf.init_parent(capacity)
    seen = jnp.zeros((capacity,), bool)
    p1, s1 = jax.jit(fold)(parent, seen, jnp.asarray(wire.pack_edges(src, dst, 2)))
    p2, s2 = uf.union_edges_with_seen(parent, seen, src, dst, None)
    np.testing.assert_array_equal(np.asarray(p1), np.asarray(p2))
    np.testing.assert_array_equal(np.asarray(s1), np.asarray(s2))


def test_prefetcher_yields_in_order():
    n, batch = 10, 32
    batches = [_random_edges(batch, 1 << 12, seed=i) for i in range(n)]
    out = list(wire.WirePrefetcher(iter(batches), width=2, depth=3))
    assert len(out) == n
    for (buf, count), (src, dst) in zip(out, batches):
        assert count == batch
        s, d = wire.unpack_edges(buf, batch, 2)
        np.testing.assert_array_equal(np.asarray(s), src)
        np.testing.assert_array_equal(np.asarray(d), dst)


def test_prefetcher_early_close_releases_producer():
    def endless():
        i = 0
        while True:
            yield _random_edges(16, 1 << 10, seed=i)
            i += 1

    pf = wire.WirePrefetcher(endless(), width=2, depth=2)
    it = iter(pf)
    next(it)
    pf.close()
    for t in pf._threads:
        t.join(timeout=5.0)
    assert not any(t.is_alive() for t in pf._threads)


def test_prefetcher_propagates_errors():
    def gen():
        yield _random_edges(8, 100)
        raise RuntimeError("source failed")

    it = iter(wire.WirePrefetcher(gen(), width=2, depth=2))
    next(it)
    with pytest.raises(RuntimeError, match="source failed"):
        list(it)


# ---------------------------------------------------------------------------
# EF40: sorted Elias-Fano multiset encoding (order-free folds)


def test_ef40_roundtrip_sorted_multiset():
    import jax.numpy as jnp

    cap = 1 << 12
    src, dst = _random_edges(777, cap, seed=13)
    buf = wire.pack_edges(src, dst, (wire.EF40, cap))
    assert buf.shape == (wire.ef40_nbytes(777, cap),)
    s, d = wire.unpack_edges_ef40(jnp.asarray(buf), 777, cap)
    s, d = np.asarray(s), np.asarray(d)
    # the batch comes back GROUPED by src (nondecreasing): same multiset,
    # not the arrival sequence
    assert (np.diff(s) >= 0).all()
    w_in = np.sort(src.astype(np.int64) << 20 | dst.astype(np.int64))
    w_out = np.sort(s.astype(np.int64) << 20 | d.astype(np.int64))
    np.testing.assert_array_equal(w_out, w_in)


def test_ef40_native_matches_numpy(monkeypatch):
    lib = load_ingest_lib()
    if lib is None or not hasattr(lib, "pack_edges_ef40"):
        pytest.skip("native pack_edges_ef40 unavailable")
    cap = 1 << 10
    src, dst = _random_edges(500, cap, seed=14)
    native_buf = wire.pack_edges(src, dst, (wire.EF40, cap))
    monkeypatch.setattr(wire, "load_ingest_lib", lambda: None)
    numpy_buf = wire.pack_edges(src, dst, (wire.EF40, cap))
    np.testing.assert_array_equal(native_buf, numpy_buf)


def test_ef40_native_blocked_path_matches_numpy(monkeypatch):
    """Parity on the cache-blocked native sort (capacity > 2^14, n >= 2^16).

    The native pack switches to a two-level bucketed counting sort at scale;
    these shapes force that path — including a capacity that is not a
    multiple of the 2^12 bucket span (partial last bucket) and odd n — so
    a regression in the bucket scatter or the done-based prefix cannot ship
    behind the small-shape parity test above.
    """
    lib = load_ingest_lib()
    if lib is None or not hasattr(lib, "pack_edges_ef40"):
        pytest.skip("native pack_edges_ef40 unavailable")
    for n, cap, seed in [
        ((1 << 16) + 1, 1 << 20, 15),       # blocked, odd n, full capacity
        (1 << 16, (1 << 20) - 333, 16),     # partial last bucket
        ((1 << 16) + 7, (1 << 15) + 5, 17), # small odd capacity, odd n
    ]:
        src, dst = _random_edges(n, cap, seed=seed)
        src[: n // 8] = 42  # skewed hot vertex crossing bucket boundaries
        native_buf = wire.pack_edges(src, dst, (wire.EF40, cap))
        with monkeypatch.context() as m:
            m.setattr(wire, "load_ingest_lib", lambda: None)
            numpy_buf = wire.pack_edges(src, dst, (wire.EF40, cap))
        np.testing.assert_array_equal(native_buf, numpy_buf)


def test_ef40_odd_and_duplicate_edges():
    import jax.numpy as jnp

    cap = 64
    src = np.array([3, 3, 3, 0, 63], np.int32)
    dst = np.array([5, 5, 1, 0, 63], np.int32)  # duplicates + self loops
    buf = wire.pack_edges(src, dst, (wire.EF40, cap))
    s, d = wire.unpack_edges_ef40(jnp.asarray(buf), 5, cap)
    np.testing.assert_array_equal(np.asarray(s), [0, 3, 3, 3, 63])
    # dst within a src group keeps arrival order (stable grouping)
    np.testing.assert_array_equal(np.asarray(d), [0, 5, 5, 1, 63])


def test_ef40_bytes_beat_pair40_at_scale():
    n, cap = 1 << 16, 1 << 16
    assert wire.ef40_nbytes(n, cap) < 5 * n * 0.6  # < 3 B/edge here


def test_records48_roundtrip():
    import jax

    rng = np.random.default_rng(17)
    ids = rng.integers(0, 1 << 20, 1000).astype(np.int32)
    vals = rng.integers(0, 1 << 28, 1000).astype(np.int32)
    mask = rng.random(1000) < 0.7
    import jax.numpy as jnp

    packed = jax.jit(wire.pack_records48)(jnp.asarray(ids), jnp.asarray(vals))
    bits = jax.jit(wire.pack_mask_bits)(jnp.asarray(mask))
    assert packed.shape == (6000,) and bits.shape == (125,)
    i2, v2, m2 = wire.unpack_records48(np.asarray(packed), np.asarray(bits), 1000)
    np.testing.assert_array_equal(i2, ids)
    np.testing.assert_array_equal(v2, vals)
    np.testing.assert_array_equal(m2, mask)


def test_replay_width_picks_cheapest_legal_encoding():
    """EF40 only wins while its per-batch bitvector is outweighed by the
    2.5 B/edge dst stream; capacity >> batch must fall back to fixed width."""
    from gelly_streaming_tpu.io import wire

    # capacity small vs batch: EF40 strictly smaller
    assert wire.replay_width(1 << 10, 4096) == (wire.EF40, 1 << 10)
    # capacity 2^20 with a tiny batch: the bitvector alone is ~32 B/edge
    assert wire.replay_width(1 << 20, 4096) == wire.PAIR40
    # order-sensitive folds never get the multiset encoding
    assert wire.replay_width(1 << 10, 4096, order_free=False) == 2
    # ids beyond 20 bits: EF40 illegal regardless
    assert wire.replay_width((1 << 20) + 1, 1 << 22) == 3
    # the chosen encoding really is the cheaper of the two at the boundary
    for cap, batch in [(1 << 16, 1 << 14), (1 << 20, 1 << 21), (1 << 18, 1 << 16)]:
        w = wire.replay_width(cap, batch)
        fixed = wire.width_for_capacity(cap)
        best = min(
            wire.wire_nbytes(batch, fixed), wire.ef40_nbytes(batch, cap)
        )
        assert wire.wire_nbytes(batch, w) == best

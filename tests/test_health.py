"""Streaming health plane (ISSUE 10): lag/keep-up gauges, SLO burn-rate
alerting, and the structured event journal.

The contracts under test:

* GAUGES — ``NetworkEdgeSource.progress`` surfaces the exact positional
  accounting ``ready()`` already does (watermark lag) plus backlog depth
  and AGE from the queue's enqueue timestamps; the scheduler's sampler
  turns them into EWMA keep-up verdicts with zero device syncs.
* SLO MONITOR — deterministic, injected-clock walks through the
  OK -> WARN -> PAGE state machine with fast+slow burn windows and
  clear-hold hysteresis; instance pruning retires a dead job's alerts.
* FAULT INJECTION — a deliberately slow sink (tiny emission queue + a
  1-record results buffer nobody drains) drives backlog-age past its SLO
  through WARN -> PAGE; recovery clears; every transition is visible in
  the ``health`` verb, the job's status row, the Prometheus exposition,
  and the event journal — and replaying the journal file reconstructs the
  job's full lifecycle.
* INVARIANTS — monitoring fully on (sampling + SLOs + journal file) vs
  fully off: bit-identical emissions and zero extra recompiles across the
  wire / windowed / async / superbatch planes.

Every threaded test carries ``timeout_cap``.
"""

import dataclasses
import json
import os
import threading
import time

import numpy as np
import pytest

from gelly_streaming_tpu.core.config import (
    RuntimeConfig,
    ServerConfig,
    SLOSpec,
    StreamConfig,
)
from gelly_streaming_tpu.core.stream import EdgeStream
from gelly_streaming_tpu.io.sources import NetworkEdgeSource
from gelly_streaming_tpu.library.connected_components import (
    ConnectedComponents,
)
from gelly_streaming_tpu.runtime import JobManager
from gelly_streaming_tpu.runtime.client import GellyClient
from gelly_streaming_tpu.runtime.server import StreamServer
from gelly_streaming_tpu.runtime.slo import SLOMonitor
from gelly_streaming_tpu.utils import events, metrics

pytestmark = pytest.mark.timeout_cap(300)

CAP = 1 << 12
W = 1 << 10
B = 1 << 9


def _graph(seed: int, n: int, cap: int = CAP):
    rng = np.random.default_rng(seed)
    return (
        rng.integers(0, cap, n).astype(np.int32),
        rng.integers(0, cap, n).astype(np.int32),
    )


def _reset_health_state():
    metrics.reset_alerts()
    metrics.reset_job_health()
    metrics.reset_histograms()
    events.configure(path=None)


# ---------------------------------------------------------------------------
# keep-up tracker + progress probe units
# ---------------------------------------------------------------------------


def test_keepup_tracker_converges_to_sustained_rates():
    tr = metrics.KeepUpTracker(halflife_s=2.0)
    assert tr.sample(0.0, 0, 0) == (0.0, 0.0)  # anchor sample
    for t in range(1, 40):
        arrival, drain = tr.sample(float(t), t * 1000, t * 400)
    assert arrival == pytest.approx(1000.0, rel=0.01)
    assert drain == pytest.approx(400.0, rel=0.01)
    # a one-tick burst moves the EWMA by less than half its weight
    arrival, _ = tr.sample(40.0, 39 * 1000 + 10_000, 40 * 400)
    assert arrival < 4000


def test_keepup_tracker_ignores_non_advancing_clock():
    tr = metrics.KeepUpTracker()
    tr.sample(1.0, 0, 0)
    tr.sample(2.0, 100, 100)
    before = (tr.arrival_eps, tr.drain_eps)
    assert tr.sample(2.0, 500, 500) == before  # dt == 0: no divide, no move


def test_network_source_progress_probe():
    cfg = StreamConfig(
        vertex_capacity=CAP, batch_size=B, ingest_window_edges=W
    )
    src = NetworkEdgeSource(cfg, B, max_queued_batches=8)
    p0 = src.progress()
    assert p0["backlog_batches"] == 0 and p0["backlog_age_s"] == 0.0
    assert p0["queue_capacity_edges"] == 8 * B
    s, d = _graph(0, B)
    for _ in range(6):  # 3072 edges -> windows 0,1 closable, none delivered
        src.push_tail(s, d)
    time.sleep(0.05)
    p = src.progress()
    assert p["backlog_batches"] == 6
    assert p["edges_in"] == 6 * B
    assert p["closable_windows"] == 2 and p["delivered_windows"] == 0
    assert p["backlog_age_s"] >= 0.05  # the oldest push has been waiting
    # drain one window's worth through the factory: lag closes, and the
    # held tail batch no longer ages (trickling != falling behind)
    it = src._factory()
    consumed = 0
    while src.progress()["delivered_windows"] < 2:
        next(it)
        consumed += 1
    p2 = src.progress()
    assert p2["closable_windows"] == p2["delivered_windows"] == 2
    assert p2["backlog_age_s"] == 0.0
    it.close()


def test_network_source_progress_applies_resume_floor():
    """After a restore, the checkpoint-covered filler region is DELIVERED
    as far as lag is concerned (those windows replay-skip) — the same
    floor ready() applies.  Without it every restart would page a
    watermark-lag/backlog-age SLO until the client streamed past the
    cursor."""
    cfg = StreamConfig(
        vertex_capacity=CAP, batch_size=B, ingest_window_edges=W
    )
    src = NetworkEdgeSource(cfg, B, resume_edges=4 * W, max_queued_batches=8)
    p = src.progress()
    # filler counts as accepted AND as delivered: an idle restored job is
    # fully caught up, not 3 windows behind
    assert p["closable_windows"] == 3  # (4W - 1) // W
    assert p["delivered_windows"] == 4  # the resume floor
    assert p["backlog_age_s"] == 0.0
    s, d = _graph(4, B)
    src.push_tail(s, d)  # the first post-cursor batch, held for its window
    p2 = src.progress()
    assert p2["closable_windows"] == 4 and p2["delivered_windows"] == 4
    assert p2["backlog_age_s"] == 0.0  # held tail, not lag


def test_sampler_replaces_rows_when_probe_stops_producing():
    """A probe that dies mid-life must not leave last sweep's backlog/lag
    frozen in the health row driving SLO verdicts (job_health_set
    replaces; the sink-side row carries no probe-derived keys)."""
    metrics.reset_job_health()
    metrics.job_health_set("j", {"backlog_age_s": 30.0, "drain_eps": 1.0})
    metrics.job_health_set("j", {"drain_eps": 2.0, "out_queue_depth": 0})
    assert "backlog_age_s" not in metrics.job_health("j")


# ---------------------------------------------------------------------------
# event journal units
# ---------------------------------------------------------------------------


def test_journal_ring_tail_and_filters():
    j = events.EventJournal(capacity=8, clock=lambda: 123.0)
    for i in range(12):
        j.emit("job_transition", job=f"j{i % 2}", **{"from": "A", "to": "B"})
    j.emit("alert", scope="job", id="j0")
    tail = j.tail(4)
    assert [e["seq"] for e in tail] == [9, 10, 11, 12]
    assert all(e["ts"] == 123.0 for e in tail)
    assert {e["job"] for e in j.tail(8, job="j1")} == {"j1"}
    assert [e["kind"] for e in j.tail(8, kind="alert")] == ["alert"]
    stats = j.stats()
    assert stats["events_emitted"] == 13 and stats["events_held"] == 8


def test_journal_file_rotation_and_replay(tmp_path):
    path = str(tmp_path / "events.jsonl")
    j = events.EventJournal(path=path, max_bytes=600, keep=2)
    for i in range(40):
        j.emit("job_transition", job="j", **{"from": "A", "to": "B"}, i=i)
    j.close()
    assert os.path.exists(path + ".1")  # rotated at least once
    replayed = events.replay(path)
    assert replayed and all(e["kind"] == "job_transition" for e in replayed)
    seqs = [e["seq"] for e in replayed]
    assert seqs == sorted(seqs)
    assert j.stats()["events_write_errors"] == 0


def test_journal_replay_reconstructs_lifecycle(tmp_path):
    path = str(tmp_path / "events.jsonl")
    j = events.EventJournal(path=path)
    j.emit("job_submitted", job="t/x")
    for frm, to in (
        ("PENDING", "RUNNING"),
        ("RUNNING", "PAUSED"),
        ("PAUSED", "RUNNING"),
        ("RUNNING", "DRAINING"),
        ("DRAINING", "DONE"),
    ):
        j.emit("job_transition", job="t/x", **{"from": frm, "to": to})
    j.emit("job_transition", job="t/other", **{"from": "PENDING", "to": "FAILED"})
    j.close()
    evts = events.replay(path)
    assert events.job_lifecycle(evts, "t/x") == [
        "PENDING",
        "RUNNING",
        "PAUSED",
        "RUNNING",
        "DRAINING",
        "DONE",
    ]
    # a broken chain is loud, never silently bridged
    j2 = events.EventJournal()
    j2.emit("job_submitted", job="g")
    j2.emit("job_transition", job="g", **{"from": "RUNNING", "to": "DONE"})
    with pytest.raises(ValueError, match="journal gap"):
        events.job_lifecycle(j2.tail(10), "g")


def test_journal_replay_tolerates_torn_tail(tmp_path):
    path = str(tmp_path / "events.jsonl")
    j = events.EventJournal(path=path)
    j.emit("job_submitted", job="a")
    j.emit("job_submitted", job="b")
    j.close()
    with open(path, "a") as f:
        f.write('{"seq": 2, "kind": "job_tr')  # crash mid-write
    assert [e["job"] for e in events.replay(path)] == ["a", "b"]


def test_journal_tail_zero_returns_nothing():
    j = events.EventJournal()
    j.emit("alert")
    assert j.tail(0) == [] and j.tail(-3) == []
    assert len(j.tail(1)) == 1


def test_journal_seq_orders_submit_before_first_transition():
    """job_submitted must outrun the scheduler's PENDING->RUNNING in seq
    order (it is journaled under the manager lock, before the scheduler
    can touch the job) — else replay's lifecycle chain breaks."""
    _reset_health_state()
    cfg = StreamConfig(
        vertex_capacity=CAP, batch_size=B, ingest_window_edges=W
    )
    with JobManager() as jm:
        for i in range(6):
            s, d = _graph(i, W)
            job = jm.submit_aggregation(
                EdgeStream.from_arrays(s, d, cfg),
                ConnectedComponents(),
                name=f"seq-{i}",
            )
            list(job.results())
    evs = events.journal().tail(200)
    for i in range(6):
        assert events.job_lifecycle(evs, f"seq-{i}")[-1] == "DONE"


def test_broken_progress_probe_degrades_not_kills_scheduler():
    """A user-supplied probe returning a malformed dict must cost a gauge
    sweep, never the ONE scheduler thread (the loop's 'never kill the
    loop' invariant extends to sampling)."""
    _reset_health_state()
    cfg = StreamConfig(
        vertex_capacity=CAP, batch_size=B, ingest_window_edges=W
    )
    s, d = _graph(9, 4 * W)
    with JobManager(RuntimeConfig(health_sample_s=0.001)) as jm:
        job = jm.submit(
            lambda: iter(
                EdgeStream.from_arrays(s, d, cfg).aggregate(
                    ConnectedComponents()
                )
            ),
            name="badprobe",
            progress=lambda: {"edges_in": 1},  # missing every other key
        )
        out = list(job.results())
        assert len(out) == 4  # the job still ran to completion
        # and the scheduler survives to run ANOTHER job afterwards
        job2 = jm.submit_aggregation(
            EdgeStream.from_arrays(s, d, cfg),
            ConnectedComponents(),
            name="after",
        )
        assert len(list(job2.results())) == 4


def test_journal_concurrent_emitters_lose_nothing():
    j = events.EventJournal(capacity=4096)

    def emitter(k):
        for i in range(200):
            j.emit("alert", worker=k, i=i)

    threads = [
        threading.Thread(target=emitter, args=(k,)) for k in range(8)
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert j.stats()["events_emitted"] == 1600
    seqs = [e["seq"] for e in j.tail(4096)]
    assert len(seqs) == len(set(seqs)) == 1600  # no duplicated/lost seq


# ---------------------------------------------------------------------------
# SLO spec + monitor (deterministic, injected clocks)
# ---------------------------------------------------------------------------


def test_slo_spec_validation():
    with pytest.raises(ValueError, match="unknown SLO metric"):
        SLOSpec(metric="p99_nonsense", threshold=1.0)
    with pytest.raises(ValueError, match="job-scope only"):
        SLOSpec(metric="max_backlog_age_s", threshold=1.0, scope="tenant")
    with pytest.raises(ValueError, match="fast_window_s"):
        SLOSpec(
            metric="max_backlog_age_s",
            threshold=1.0,
            fast_window_s=10,
            slow_window_s=5,
        )
    with pytest.raises(ValueError, match="warn_burn"):
        SLOSpec(
            metric="max_backlog_age_s",
            threshold=1.0,
            warn_burn=5.0,
            page_burn=1.0,
        )
    spec = SLOSpec(metric="p99_window_close_to_emission_ms", threshold=50.0)
    assert spec.kind() == ("hist", "window_close_to_emission_ms", 99.0)
    assert spec.budget() == pytest.approx(0.01)
    gauge = SLOSpec(metric="min_keepup_ratio", threshold=0.9)
    assert gauge.kind() == ("gauge", "keepup_ratio", "lt")
    assert gauge.budget() == pytest.approx(0.1)


def _gauge_spec(**kw):
    base = dict(
        metric="max_backlog_age_s",
        threshold=5.0,
        error_budget=0.5,
        fast_window_s=10.0,
        slow_window_s=30.0,
        warn_burn=1.0,
        page_burn=1.5,
        clear_hold=2,
    )
    base.update(kw)
    return SLOSpec(**base)


def test_slo_monitor_walks_warn_page_clear_deterministically():
    _reset_health_state()
    journal = events.EventJournal(clock=lambda: 0.0)
    t = [0.0]
    mon = SLOMonitor((_gauge_spec(),), clock=lambda: t[0], journal=journal)
    transitions = []
    metrics.job_health_update("t/j", {"backlog_age_s": 0.0})
    for tick in range(60):
        t[0] = float(tick)
        bad = 3 <= tick <= 12
        metrics.job_health_update(
            "t/j", {"backlog_age_s": 10.0 if bad else 0.0}
        )
        for tr in mon.evaluate_once():
            transitions.append((tick, tr["from"], tr["to"]))
    # the exact deterministic walk: escalation through WARN to PAGE while
    # the injected gauge violates, stepwise hysteretic clear afterwards
    assert [(frm, to) for _t, frm, to in transitions] == [
        ("OK", "WARN"),
        ("WARN", "PAGE"),
        ("PAGE", "WARN"),
        ("WARN", "OK"),
    ]
    ticks = [tick for tick, _f, _to in transitions]
    assert ticks == sorted(ticks)
    row = metrics.alert_state("job", "t/j", "max_backlog_age_s")
    assert row["state"] == "OK" and row["value"] == 0.0
    # the journal saw the same four transitions, in order
    alert_events = journal.tail(100, kind="alert")
    assert [(e["from"], e["to"]) for e in alert_events] == [
        ("OK", "WARN"),
        ("WARN", "PAGE"),
        ("PAGE", "WARN"),
        ("WARN", "OK"),
    ]
    assert all(e["id"] == "t/j" for e in alert_events)


def test_slo_monitor_needs_both_windows_to_page():
    """A violation shorter than the slow window's budget share cannot
    PAGE: the fast window saturates but the slow window stays under the
    page burn — the multiwindow rule that keeps blips from paging."""
    _reset_health_state()
    t = [0.0]
    spec = _gauge_spec(slow_window_s=40.0, page_burn=1.9)
    mon = SLOMonitor((spec,), clock=lambda: t[0])
    states = set()
    for tick in range(80):
        t[0] = float(tick)
        bad = 10 <= tick < 22  # 12 bad ticks; slow frac caps ~12/40 = 0.3
        metrics.job_health_update(
            "solo", {"backlog_age_s": 10.0 if bad else 0.0}
        )
        mon.evaluate_once()
        states.add(metrics.alert_state("job", "solo", spec.alert_name())["state"])
    assert "WARN" in states and "PAGE" not in states


def test_slo_monitor_histogram_metric_burns_on_windowed_deltas():
    _reset_health_state()
    spec = SLOSpec(
        metric="p99_window_close_to_emission_ms",
        threshold=8.0,  # a bucket boundary: 2^3 ms (boundary-exact)
        error_budget=0.25,
        fast_window_s=4.0,
        slow_window_s=12.0,
        warn_burn=1.0,
        page_burn=2.0,
        clear_hold=2,
    )
    t = [0.0]
    mon = SLOMonitor((spec,), clock=lambda: t[0])
    transitions = []
    for tick in range(40):
        t[0] = float(tick)
        # 10 fast samples per tick until tick 10, then all slow until 20,
        # then fast again — the windowed DELTAS drive the burn, so old
        # fast samples cannot dilute a fresh stall
        ms = 1.0 if (tick < 10 or tick >= 20) else 100.0
        for _ in range(10):
            metrics.hist_record(
                "window_close_to_emission_ms", ms, job="t/h"
            )
        for tr in mon.evaluate_once():
            transitions.append((tr["from"], tr["to"]))
    assert transitions[:2] == [("OK", "WARN"), ("WARN", "PAGE")]
    assert transitions[-1][1] == "OK"


def test_slo_monitor_prunes_dead_instances_and_retires_alerts():
    _reset_health_state()
    t = [0.0]
    spec = _gauge_spec()
    mon = SLOMonitor((spec,), clock=lambda: t[0])
    for tick in range(8):
        t[0] = float(tick)
        metrics.job_health_update("gone", {"backlog_age_s": 50.0})
        mon.evaluate_once()
    assert metrics.alert_state("job", "gone", spec.alert_name())["state"] != "OK"
    # the job terminates: its health row is dropped (the sampler's
    # terminal sweep) -> next evaluation prunes the instance AND its alert
    metrics.drop_job_health("gone")
    t[0] = 8.0
    mon.evaluate_once()
    assert metrics.alert_state("job", "gone", spec.alert_name()) is None
    assert mon.stats()["instances"] == 0


# ---------------------------------------------------------------------------
# manager sampling (non-network jobs get sink-side gauges)
# ---------------------------------------------------------------------------


def test_scheduler_samples_health_gauges_for_plain_jobs():
    _reset_health_state()
    cfg = StreamConfig(
        vertex_capacity=CAP, batch_size=B, ingest_window_edges=W
    )
    s, d = _graph(1, 8 * W)
    gate = threading.Event()

    def slow_sink(rec):
        gate.wait(0.02)  # keep the job alive across sampling ticks

    rt = RuntimeConfig(health_sample_s=0.005)
    with JobManager(rt) as jm:
        job = jm.submit_aggregation(
            EdgeStream.from_arrays(s, d, cfg),
            ConnectedComponents(),
            name="plain",
            sink=slow_sink,
        )
        deadline = time.monotonic() + 60
        while time.monotonic() < deadline:
            if metrics.job_health("plain"):
                break
            time.sleep(0.005)
        row = metrics.job_health("plain")
        assert "out_queue_depth" in row and "drain_eps" in row
        gate.set()
        assert job.wait(60)
        # the terminal transition drops the gauge row (no stale backlog
        # keeping an SLO alert burning on a DONE job)
        deadline = time.monotonic() + 30
        while time.monotonic() < deadline and metrics.job_health("plain"):
            time.sleep(0.01)
        assert metrics.job_health("plain") == {}


# ---------------------------------------------------------------------------
# the fault-injection acceptance walk: slow sink -> WARN -> PAGE -> clear
# ---------------------------------------------------------------------------


def test_alert_lifecycle_slow_sink_warn_page_clear(tmp_path):
    metrics.reset_alerts()
    metrics.reset_job_health()
    metrics.reset_histograms()
    journal_path = str(tmp_path / "events.jsonl")
    events.configure(path=journal_path)
    try:
        spec = SLOSpec(
            metric="max_backlog_age_s",
            threshold=0.15,
            error_budget=0.5,
            fast_window_s=0.4,
            slow_window_s=1.0,
            warn_burn=1.0,
            page_burn=1.5,
            clear_hold=2,
        )
        # tiny emission queue + 1-record results buffer = the deliberately
        # slow sink: the scheduler can absorb ~3 windows, everything else
        # backs up in the source queue and AGES
        rt = RuntimeConfig(
            health_sample_s=0.03,
            slos=(spec,),
            slo_interval_s=0.05,
            job_queue_depth=2,
        )
        n = 32 * W
        s, d = _graph(2, n)
        with JobManager(rt) as jm, StreamServer(
            jm, ServerConfig(result_buffer_records=1)
        ) as server:
            with GellyClient("127.0.0.1", server.port) as c:
                c.submit(
                    name="hj", query="cc", capacity=CAP, window_edges=W, batch=B
                )
                c.push_edges("hj", s, d, batch=B, capacity=CAP, close=False)
                key = ("job", "default/hj", "max_backlog_age_s")

                def wait_state(want, deadline_s):
                    deadline = time.monotonic() + deadline_s
                    while time.monotonic() < deadline:
                        al = metrics.alert_state(*key)
                        if al and al["state"] == want:
                            return al
                        time.sleep(0.01)
                    raise AssertionError(
                        f"alert never reached {want}; last: "
                        f"{metrics.alert_state(*key)}"
                    )

                paged = wait_state("PAGE", 120)
                assert paged["burn_fast"] >= spec.page_burn
                # visible in the health verb...
                h = c.health()
                gauges = h["jobs"]["default/hj"]
                assert gauges["watermark_lag_windows"] > 0
                assert gauges["backlog_age_s"] > spec.threshold
                assert gauges["keepup_ratio"] < 1.0
                assert any(
                    a["id"] == "default/hj" and a["state"] == "PAGE"
                    for a in h["alerts"]
                )
                assert h["monitor"]["running"] and h["monitor"]["specs"] == 1
                assert h["slos"][0]["metric"] == "max_backlog_age_s"
                # ...the job's status row...
                row = c.status()["status"]["jobs"]["default/hj"]
                assert row["health"]["backlog_age_s"] > spec.threshold
                assert [a["state"] for a in row["alerts"]] == ["PAGE"]
                # ...and the Prometheus exposition
                text = c.metrics_prometheus()
                assert (
                    'gelly_slo_state{scope="job",id="default/hj",'
                    'slo="max_backlog_age_s"} 2' in text
                )
                assert "gelly_backlog_age_s" in text

                # recovery: a consumer starts draining -> backlog empties
                # -> the alert walks back down and CLEARS
                stop = threading.Event()
                got = []

                def consume():
                    with GellyClient("127.0.0.1", server.port) as c2:
                        while not stop.is_set():
                            recs, _st, eos = c2.results("hj", timeout_ms=300)
                            got.extend(recs)
                            if eos:
                                return

                th = threading.Thread(target=consume, daemon=True)
                th.start()
                cleared = wait_state("OK", 120)
                assert cleared["burn_fast"] < spec.warn_burn
                c.eos("hj")
                assert jm.wait_all(120)
                stop.set()
                th.join(30)
                assert len(got) == 32  # every window's record delivered

                # the journal recorded the whole story, in order
                evs = c.events(400)
                alert_seq = [
                    (e["from"], e["to"]) for e in evs if e["kind"] == "alert"
                ]
                assert alert_seq[0] == ("OK", "WARN")
                assert ("WARN", "PAGE") in alert_seq
                assert alert_seq[-1][1] == "OK"
                # every transition is a single step of the state machine
                for frm, to in alert_seq:
                    assert (
                        abs(
                            metrics.ALERT_LEVELS[to]
                            - metrics.ALERT_LEVELS[frm]
                        )
                        == 1
                    )
        # replaying the JSONL file reconstructs the job's full lifecycle
        replayed = events.replay(journal_path)
        assert events.job_lifecycle(replayed, "default/hj") == [
            "PENDING",
            "RUNNING",
            "DRAINING",
            "DONE",
        ]
        replay_alerts = [
            (e["from"], e["to"])
            for e in replayed
            if e["kind"] == "alert" and e["id"] == "default/hj"
        ]
        assert replay_alerts[0] == ("OK", "WARN")
        assert ("WARN", "PAGE") in replay_alerts
    finally:
        events.configure(path=None)


def test_admission_reject_lands_in_journal():
    _reset_health_state()
    cfg = StreamConfig(
        vertex_capacity=CAP, batch_size=B, ingest_window_edges=W
    )
    s, d = _graph(3, 2 * W)
    with JobManager(RuntimeConfig(max_jobs=1)) as jm:
        jm.submit_aggregation(
            EdgeStream.from_arrays(s, d, cfg),
            ConnectedComponents(),
            name="only",
        )
        from gelly_streaming_tpu.runtime import AdmissionError

        with pytest.raises(AdmissionError):
            jm.submit_aggregation(
                EdgeStream.from_arrays(s, d, cfg),
                ConnectedComponents(),
                name="over",
            )
        rejects = events.journal().tail(50, kind="admission_reject")
        assert rejects and rejects[-1]["job"] == "over"
        assert "job cap" in rejects[-1]["reason"]
        # the sink-less job's queue is nobody's to drain here: the context
        # exit cancels it (journaled like every other transition)


# ---------------------------------------------------------------------------
# invariants: monitoring on/off — bit-identical emissions, 0 recompiles
# ---------------------------------------------------------------------------

CFG_WIRE = StreamConfig(
    vertex_capacity=CAP, batch_size=B, ingest_window_edges=W
)
CFG_WINDOWED = StreamConfig(
    vertex_capacity=CAP, batch_size=B + 96, ingest_window_edges=W
)


@pytest.mark.parametrize(
    "cfg",
    [
        CFG_WIRE,
        CFG_WINDOWED,
        dataclasses.replace(CFG_WINDOWED, async_windows=2),
        dataclasses.replace(CFG_WIRE, superbatch=2),
    ],
    ids=["wire", "windowed", "async", "superbatch"],
)
def test_monitoring_on_off_identical_emissions_zero_recompiles(
    cfg, tmp_path
):
    s, d = _graph(7, 8 * W)

    def run(rt_cfg):
        with JobManager(rt_cfg) as jm:
            job = jm.submit_aggregation(
                EdgeStream.from_arrays(s, d, cfg),
                ConnectedComponents(),
                name="inv",
            )
            return [np.asarray(rec[0].parent) for rec in job.results()]

    _reset_health_state()
    off = run(RuntimeConfig(health_sample_s=0.0))
    metrics.reset_compile_cache_stats()
    on = run(
        RuntimeConfig(
            health_sample_s=0.002,
            slo_interval_s=0.01,
            slos=(
                SLOSpec(
                    metric="p99_window_close_to_emission_ms",
                    threshold=8.0,
                    fast_window_s=0.05,
                    slow_window_s=0.2,
                ),
                _gauge_spec(),
            ),
        )
    )
    recompiles = metrics.compile_cache_stats()["recompiles"]
    events.configure(path=None)
    assert recompiles == 0
    assert len(off) == len(on)
    for w, (a, b) in enumerate(zip(off, on)):
        assert np.array_equal(a, b), f"window {w} diverged with monitoring on"


# ---------------------------------------------------------------------------
# gelly-top --json + events verb scoping
# ---------------------------------------------------------------------------


def test_top_frame_dict_is_machine_readable():
    from gelly_streaming_tpu.runtime.top import frame_dict

    status = {
        "server": {"connections": 1, "served_jobs": 1, "port": 7},
        "status": {
            "jobs": {"t/j": {"state": "RUNNING", "job_edges": 20_000}}
        },
    }
    snap = {"tenants": {"t": {"tenant_requests": 1}}, "pipeline": {}}
    health = {
        "jobs": {"t/j": {"keepup_ratio": 0.5}},
        "alerts": [{"scope": "job", "id": "t/j", "state": "WARN"}],
    }
    frame = frame_dict(status, snap, {"t/j": 10_000}, 2.0, health)
    assert frame["jobs"]["t/j"]["eps"] == pytest.approx(5000.0)
    assert frame["health"]["t/j"]["keepup_ratio"] == 0.5
    assert frame["alerts"][0]["state"] == "WARN"
    json.dumps(frame)  # JSON-serializable end to end
    # first frame: no delta yet
    assert frame_dict(status, snap, None, None)["jobs"]["t/j"]["eps"] is None


def test_gelly_top_once_json_emits_exactly_one_object(capsys):
    _reset_health_state()
    from gelly_streaming_tpu.runtime import top as top_mod

    n = 4 * W
    s, d = _graph(5, n)
    with JobManager(RuntimeConfig(health_sample_s=0.01)) as jm, StreamServer(
        jm, ServerConfig()
    ) as server:
        with GellyClient("127.0.0.1", server.port) as c:
            c.submit(
                name="tj", query="cc", capacity=CAP, window_edges=W, batch=B
            )
            c.push_edges("tj", s, d, batch=B, capacity=CAP)
            list(c.iter_results("tj", deadline_s=240))
        rc = top_mod.main(
            ["--connect", f"127.0.0.1:{server.port}", "--once", "--json"]
        )
    assert rc == 0
    out = capsys.readouterr().out.strip()
    frame = json.loads(out)  # exactly ONE object on stdout
    assert "default/tj" in frame["jobs"]
    assert frame["jobs"]["default/tj"]["state"] == "DONE"
    assert "health" in frame and "alerts" in frame


def test_events_verb_is_tenant_scoped():
    _reset_health_state()
    from gelly_streaming_tpu.core.config import TenantConfig

    cfg = ServerConfig(
        tenants=(
            TenantConfig(tenant="a", token="tok-a"),
            TenantConfig(tenant="b", token="tok-b"),
        )
    )
    n = 2 * W
    s, d = _graph(6, n)
    with JobManager() as jm, StreamServer(jm, cfg) as server:
        with GellyClient("127.0.0.1", server.port, token="tok-a") as c:
            c.submit(
                name="mine", query="cc", capacity=CAP, window_edges=W, batch=B
            )
            c.push_edges("mine", s, d, batch=B, capacity=CAP)
            list(c.iter_results("mine", deadline_s=240))
            mine = c.events(200)
            assert any(e.get("job") == "a/mine" for e in mine)
        with GellyClient("127.0.0.1", server.port, token="tok-b") as c:
            other = c.events(200)
            assert not any(
                str(e.get("job", "")).startswith("a/") for e in other
            )

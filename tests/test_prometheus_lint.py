"""Prometheus text-format lint (ISSUE 10 satellite): a strict line-grammar
validator run over ``render_prometheus()`` output — including the new
lag/health gauges and SLO alert families.

The exposition format's rules are easy to violate incrementally (a label
with a raw quote, a family's series interleaved between other families,
a histogram whose cumulative buckets dip): a scraper then drops the whole
scrape, which is exactly when the metrics mattered.  ``lint_prometheus``
enforces:

* line grammar — every line is a ``# HELP``/``# TYPE`` comment or a
  ``name{labels} value`` sample with legal metric/label names, properly
  escaped label values (only ``\\\\``, ``\\"``, ``\\n``), and a float value;
* family grouping + metadata ordering — all samples of a family are
  contiguous, at most one HELP/TYPE each, and they precede the samples;
* histogram shape — per series, ``_bucket`` ``le`` values strictly
  increasing with non-decreasing cumulative counts, a terminal
  ``le="+Inf"`` bucket equal to ``_count``, and a ``_sum`` present.

The pre-health-plane renderer violated the grouping rule (a family's
job-labeled series interleaved per job); the rewrite is pinned here.
"""

import math
import re

import pytest

from gelly_streaming_tpu.utils import metrics

_NAME_RE = re.compile(r"^[a-zA-Z_:][a-zA-Z0-9_:]*$")
_LABEL_NAME_RE = re.compile(r"^[a-zA-Z_][a-zA-Z0-9_]*$")
# quoted label value: only \\ \" \n escapes are legal
_LABEL_VALUE_RE = re.compile(r'^(?:[^"\\\n]|\\\\|\\"|\\n)*$')
_SAMPLE_RE = re.compile(r"^([a-zA-Z_:][a-zA-Z0-9_:]*)(\{(.*)\})?\s+(\S+)$")
_HELP_RE = re.compile(r"^# HELP ([a-zA-Z_:][a-zA-Z0-9_:]*)( .*)?$")
_TYPE_RE = re.compile(
    r"^# TYPE ([a-zA-Z_:][a-zA-Z0-9_:]*) "
    r"(counter|gauge|histogram|summary|untyped)$"
)
_HIST_SUFFIXES = ("_bucket", "_sum", "_count")


def _parse_labels(raw, errors, where):
    """{'name': 'value'} from the inside of a label brace block."""
    out = {}
    if raw is None or raw == "":
        return out
    # split on commas outside quotes
    parts, depth, cur = [], False, ""
    i = 0
    while i < len(raw):
        ch = raw[i]
        if ch == "\\" and depth and i + 1 < len(raw):
            cur += raw[i : i + 2]
            i += 2
            continue
        if ch == '"':
            depth = not depth
        if ch == "," and not depth:
            parts.append(cur)
            cur = ""
        else:
            cur += ch
        i += 1
    if cur:
        parts.append(cur)
    for part in parts:
        if "=" not in part:
            errors.append(f"{where}: malformed label pair {part!r}")
            continue
        name, _, value = part.partition("=")
        if not _LABEL_NAME_RE.match(name):
            errors.append(f"{where}: bad label name {name!r}")
        if not (value.startswith('"') and value.endswith('"') and len(value) >= 2):
            errors.append(f"{where}: unquoted label value {value!r}")
            continue
        body = value[1:-1]
        if not _LABEL_VALUE_RE.match(body):
            errors.append(f"{where}: bad escaping in label value {body!r}")
        if name in out:
            errors.append(f"{where}: duplicate label {name!r}")
        out[name] = body
    return out


def _value(text, errors, where):
    if text in ("+Inf", "-Inf", "Nan", "NaN"):
        return math.inf if text == "+Inf" else -math.inf
    try:
        return float(text)
    except ValueError:
        errors.append(f"{where}: unparseable sample value {text!r}")
        return 0.0


def lint_prometheus(text):
    """Validate one exposition; returns a list of error strings ([] = clean)."""
    errors = []
    # family name -> list of (sample name, labels dict, value) in order
    families = {}
    meta = {}  # family -> {"help": line#, "type": (line#, kind)}
    order = []  # family order of first appearance (meta or sample)
    closed = set()
    typed_hist = set()

    def family_of(name):
        for suffix in _HIST_SUFFIXES:
            if name.endswith(suffix) and name[: -len(suffix)] in typed_hist:
                return name[: -len(suffix)]
        return name

    last_family = None
    for lineno, line in enumerate(text.splitlines(), start=1):
        where = f"line {lineno}"
        if not line:
            errors.append(f"{where}: empty line inside exposition")
            continue
        if line.startswith("#"):
            m = _HELP_RE.match(line)
            kind = "help"
            if m is None:
                m = _TYPE_RE.match(line)
                kind = "type"
            if m is None:
                errors.append(f"{where}: malformed comment {line!r}")
                continue
            fam = m.group(1)
            if fam not in order:
                order.append(fam)
            if fam in closed or fam in families:
                errors.append(
                    f"{where}: {kind.upper()} for {fam} after its samples "
                    "(metadata must precede the family's samples)"
                )
            if kind in meta.setdefault(fam, {}):
                errors.append(f"{where}: duplicate {kind.upper()} for {fam}")
            meta[fam][kind] = lineno
            if kind == "type" and m.group(2) == "histogram":
                typed_hist.add(fam)
            if last_family is not None and last_family != fam:
                closed.add(last_family)
            last_family = fam
            continue
        m = _SAMPLE_RE.match(line)
        if m is None:
            errors.append(f"{where}: malformed sample line {line!r}")
            continue
        name, _braced, rawlabels, value_s = m.groups()
        if not _NAME_RE.match(name):
            errors.append(f"{where}: bad metric name {name!r}")
        fam = family_of(name)
        if fam in closed:
            errors.append(
                f"{where}: family {fam} reappears after other families "
                "(all series of a family must be contiguous)"
            )
        if last_family is not None and last_family != fam:
            closed.add(last_family)
        last_family = fam
        if fam not in order:
            order.append(fam)
        labels = _parse_labels(rawlabels, errors, where)
        value = _value(value_s, errors, where)
        families.setdefault(fam, []).append((name, labels, value))

    for fam in typed_hist:
        samples = families.get(fam, [])
        # series key = labels minus le
        series = {}
        for name, labels, value in samples:
            key = tuple(
                sorted((k, v) for k, v in labels.items() if k != "le")
            )
            entry = series.setdefault(
                key, {"buckets": [], "sum": None, "count": None}
            )
            if name == f"{fam}_bucket":
                if "le" not in labels:
                    errors.append(f"{fam}: bucket sample without le label")
                    continue
                le = labels["le"]
                entry["buckets"].append(
                    (math.inf if le == "+Inf" else float(le), value)
                )
            elif name == f"{fam}_sum":
                entry["sum"] = value
            elif name == f"{fam}_count":
                entry["count"] = value
            else:
                errors.append(f"{fam}: stray series {name} in histogram")
        for key, entry in series.items():
            les = [le for le, _c in entry["buckets"]]
            counts = [c for _le, c in entry["buckets"]]
            if not les or les[-1] != math.inf:
                errors.append(f"{fam}{dict(key)}: no terminal +Inf bucket")
            if any(a >= b for a, b in zip(les, les[1:])):
                errors.append(f"{fam}{dict(key)}: le values not increasing")
            if any(a > b for a, b in zip(counts, counts[1:])):
                errors.append(
                    f"{fam}{dict(key)}: cumulative bucket counts decreased"
                )
            if entry["count"] is None or entry["sum"] is None:
                errors.append(f"{fam}{dict(key)}: missing _sum/_count")
            elif les and les[-1] == math.inf and counts[-1] != entry["count"]:
                errors.append(
                    f"{fam}{dict(key)}: +Inf bucket {counts[-1]} != "
                    f"_count {entry['count']}"
                )
    return errors


def _populated_snapshot():
    """Exercise every family shape the renderer emits: counters, job and
    tenant rows, health gauges, alerts, multi-scope histograms, spans."""
    metrics.reset_histograms()
    metrics.reset_job_health()
    metrics.reset_alerts()
    metrics.reset_job_stats()
    for ms in (0.5, 2.0, 8.0, 33.0):
        metrics.hist_record(
            "window_close_to_emission_ms", ms, job='t/esc"job\n', tenant="t"
        )
    metrics.hist_record("submit_to_first_emission_ms", 12.0, job="t/j2")
    metrics.job_add('t/esc"job\n', "job_records", 4)
    metrics.job_add("t/j2", "job_dispatches", 2)
    metrics.tenant_add("t", "tenant_requests", 7)
    metrics.job_health_update(
        't/esc"job\n',
        {
            "watermark_lag_windows": 3,
            "backlog_batches": 5,
            "backlog_age_s": 1.25,
            "arrival_eps": 1000.0,
            "drain_eps": 400.0,
            "keepup_ratio": 0.4,
            "time_to_queue_full_s": 9.5,
        },
    )
    metrics.alert_set(
        "job",
        't/esc"job\n',
        "max_backlog_age_s",
        {
            "state": "WARN",
            "burn_fast": 1.5,
            "burn_slow": 1.2,
            "threshold": 1.0,
        },
    )
    snap = metrics.metrics_snapshot()
    metrics.reset_histograms()
    metrics.reset_job_health()
    metrics.reset_alerts()
    metrics.reset_job_stats()
    return snap


def test_render_prometheus_passes_strict_lint():
    snap = _populated_snapshot()
    text = metrics.render_prometheus(snap)
    assert lint_prometheus(text) == []
    # the new health-plane families made it into the exposition
    assert "gelly_watermark_lag_windows" in text
    assert "gelly_backlog_age_s" in text
    assert "gelly_keepup_ratio" in text
    assert "gelly_slo_state" in text and "} 1" in text  # WARN -> 1
    # escaped label values survived the round trip
    assert '\\"' in text and "\\n" in text


def test_render_prometheus_groups_families_and_types():
    text = metrics.render_prometheus(_populated_snapshot())
    lines = text.splitlines()
    # every family has TYPE before its first sample
    seen_sample = set()
    for line in lines:
        if line.startswith("# TYPE "):
            fam = line.split()[2]
            assert fam not in seen_sample, f"TYPE after samples for {fam}"
        elif line and not line.startswith("#"):
            name = line.split("{")[0].split(" ")[0]
            seen_sample.add(name)
    # the multi-scope histogram family is one contiguous block
    idx = [
        i
        for i, l in enumerate(lines)
        if l.startswith("gelly_window_close_to_emission_ms")
    ]
    assert idx and idx == list(range(idx[0], idx[-1] + 1))


@pytest.mark.parametrize(
    "bad,needle",
    [
        # TYPE after the family's samples
        (
            "gelly_x 1\n# TYPE gelly_x gauge\n",
            "after its samples",
        ),
        # family interleaved
        (
            "# TYPE gelly_a gauge\ngelly_a 1\n# TYPE gelly_b gauge\n"
            "gelly_b 1\ngelly_a 2\n",
            "must be contiguous",
        ),
        # raw quote in a label value
        (
            '# TYPE gelly_a gauge\ngelly_a{job="a"b"} 1\n',
            "label",
        ),
        # non-increasing le
        (
            "# TYPE gelly_h histogram\n"
            'gelly_h_bucket{le="1.0"} 1\ngelly_h_bucket{le="1.0"} 2\n'
            'gelly_h_bucket{le="+Inf"} 2\ngelly_h_sum 3\ngelly_h_count 2\n',
            "not increasing",
        ),
        # cumulative counts decreased
        (
            "# TYPE gelly_h histogram\n"
            'gelly_h_bucket{le="1.0"} 3\ngelly_h_bucket{le="2.0"} 2\n'
            'gelly_h_bucket{le="+Inf"} 3\ngelly_h_sum 3\ngelly_h_count 3\n',
            "decreased",
        ),
        # missing terminal +Inf
        (
            "# TYPE gelly_h histogram\n"
            'gelly_h_bucket{le="1.0"} 1\ngelly_h_sum 1\ngelly_h_count 1\n',
            "+Inf",
        ),
        # +Inf bucket != _count
        (
            "# TYPE gelly_h histogram\n"
            'gelly_h_bucket{le="+Inf"} 2\ngelly_h_sum 1\ngelly_h_count 3\n',
            "_count",
        ),
        # bad metric name
        ("# TYPE gelly_a gauge\n9bad 1\n", "malformed sample"),
        # duplicate TYPE
        (
            "# TYPE gelly_a gauge\n# TYPE gelly_a gauge\ngelly_a 1\n",
            "duplicate TYPE",
        ),
    ],
)
def test_lint_catches_seeded_violations(bad, needle):
    errors = lint_prometheus(bad)
    assert errors, f"lint missed: {bad!r}"
    assert any(needle in e for e in errors), (needle, errors)


def test_lint_is_strict_about_line_grammar():
    assert lint_prometheus("# HELLO gelly_a x\n") != []
    assert lint_prometheus("# TYPE gelly_a flavor\n") != []
    assert lint_prometheus("# TYPE gelly_a gauge\ngelly_a one\n") != []

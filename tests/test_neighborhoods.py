"""Device-side degree-bucketed neighborhood build (VERDICT r1 item 6).

The round-1 build grouped panes with host numpy and padded every key to the
pane's max degree — one hub inflated the whole [K, D] tensor.  These tests pin
the bucketed build's grouping semantics (arrival order, values riding along)
and that a skewed pane's padded area stays near-linear in E instead of K*D.
"""

import jax
import jax.numpy as jnp
import numpy as np

from gelly_streaming_tpu.core.config import StreamConfig
from gelly_streaming_tpu.core.stream import EdgeStream
from gelly_streaming_tpu.core.types import EdgeDirection
from gelly_streaming_tpu.ops.neighborhoods import bucket_shapes, build_buckets


def _collect(buckets):
    """(key -> ordered neighbor list) over all buckets, ignoring padding."""
    out = {}
    for b in buckets:
        keys = np.asarray(b.keys)
        nbrs = np.asarray(b.nbrs)
        valid = np.asarray(b.valid)
        for i in range(int(b.num_keys)):
            out[int(keys[i])] = [int(n) for n, ok in zip(nbrs[i], valid[i]) if ok]
    return out


def test_grouping_matches_host_reference():
    rng = np.random.default_rng(0)
    e = 256
    src = rng.integers(0, 32, e).astype(np.int32)
    dst = rng.integers(0, 32, e).astype(np.int32)
    mask = rng.random(e) < 0.9
    got = _collect(build_buckets(jnp.asarray(src), jnp.asarray(dst), None, jnp.asarray(mask)))
    want = {}
    for s, d, m in zip(src, dst, mask):
        if m:
            want.setdefault(int(s), []).append(int(d))
    assert got == want  # arrival order preserved within keys


def test_values_ride_with_edges():
    src = jnp.asarray(np.array([3, 1, 3, 3], np.int32))
    dst = jnp.asarray(np.array([7, 8, 9, 10], np.int32))
    val = jnp.asarray(np.array([0.5, 1.5, 2.5, 3.5], np.float32))
    buckets = build_buckets(src, dst, val, jnp.ones((4,), bool))
    for b in buckets:
        keys = np.asarray(b.keys)
        for i in range(int(b.num_keys)):
            if keys[i] == 3:
                vals = np.asarray(b.vals)[i][np.asarray(b.valid)[i]]
                assert vals.tolist() == [0.5, 2.5, 3.5]


def test_hub_lands_in_its_own_bucket():
    # hub 0 with degree 100 + 100 degree-1 keys: the old single-tensor build
    # padded to [256 keys, 128 cols] = 32768 slots; bucketed area is ~6x less
    src = np.concatenate([np.zeros(100), np.arange(1, 101)]).astype(np.int32)
    dst = np.concatenate([np.arange(1, 101), np.arange(2, 102)]).astype(np.int32)
    buckets = build_buckets(
        jnp.asarray(src), jnp.asarray(dst), None, jnp.ones((200,), bool)
    )
    per_bucket_keys = [int(b.num_keys) for b in buckets]
    # degree-1 keys in bucket 0 (D=1), the hub alone in bucket ceil(log2(100))=7
    assert per_bucket_keys[0] == 100
    assert per_bucket_keys[7] == 1
    assert sum(per_bucket_keys) == 101
    used_area = sum(
        b.nbrs.shape[0] * b.nbrs.shape[1] for b in buckets if int(b.num_keys)
    )
    old_area = 128 * 128  # K_pad(101)->128 rows x D_pad(100)->128 cols
    assert used_area < old_area / 2
    assert _collect(buckets)[0] == list(range(1, 101))


def test_bucket_shapes_static_and_bounded():
    shapes = bucket_shapes(1024)
    assert shapes[0] == (1024, 1)  # all keys could have degree 1
    assert shapes[-1] == (2, 1024)  # at most 2E/D keys of max degree
    total = sum(k * d for k, d in shapes)
    assert total <= 2 * 1024 * len(shapes)  # O(E log E) padded area


def test_skewed_slice_fold_correct():
    """End-to-end: a skewed pane through slice().fold_neighbors still folds
    every neighbor exactly once per key."""
    edges = [(0, i, 1) for i in range(1, 40)] + [(i, 99, 10) for i in range(1, 5)]
    cfg = StreamConfig(vertex_capacity=128, batch_size=64)
    stream = EdgeStream.from_collection(edges, cfg)
    out = stream.slice(1000, EdgeDirection.OUT).fold_neighbors(
        (0, 0), lambda acc, vid, nbr, val: (vid, acc[1] + val)
    )
    got = dict(out.collect())
    assert got[0] == 39  # hub: 39 edges of weight 1
    for i in range(1, 5):
        assert got[i] == 10

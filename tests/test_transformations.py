"""EdgeStream transformation tests mirroring test/operations/* golden outputs."""

import jax.numpy as jnp

from gelly_streaming_tpu.core.stream import EdgeStream

from fixtures import CFG, LONG_LONG_EDGES, assert_lines, long_long_stream


def test_graph_stream_creation():
    # TestGraphStreamCreation.java:38-44
    stream = long_long_stream()
    assert_lines(
        stream.edges_csv_lines(),
        "1,2,12\n1,3,13\n2,3,23\n3,4,34\n3,5,35\n4,5,45\n5,1,51",
    )


def test_map_edges_plus_one():
    # TestMapEdges.testWithSameValue (:41-47): value + 1
    stream = long_long_stream().map_edges(lambda s, d, v: v + 1)
    assert_lines(
        stream.edges_csv_lines(),
        "1,2,13\n1,3,14\n2,3,24\n3,4,35\n3,5,36\n4,5,46\n5,1,52",
    )


def test_map_edges_to_tuple():
    # TestMapEdges tuple-type golden (:65-71): value -> (value, value+1)
    stream = long_long_stream().map_edges(lambda s, d, v: (v, v + 1))
    assert_lines(
        stream.edges_csv_lines(),
        "1,2,(12,13)\n1,3,(13,14)\n2,3,(23,24)\n3,4,(34,35)\n3,5,(35,36)\n4,5,(45,46)\n5,1,(51,52)",
    )


def test_map_edges_chained():
    # TestMapEdges chained golden (:88-94): (+1) then tuple
    stream = (
        long_long_stream()
        .map_edges(lambda s, d, v: v + 1)
        .map_edges(lambda s, d, v: (v, v + 1))
    )
    assert_lines(
        stream.edges_csv_lines(),
        "1,2,(13,14)\n1,3,(14,15)\n2,3,(24,25)\n3,4,(35,36)\n3,5,(36,37)\n4,5,(46,47)\n5,1,(52,53)",
    )


def test_filter_edges():
    # TestFilterEdges.testWithSimpleFilter (:40-44): keep value > 20
    stream = long_long_stream().filter_edges(lambda s, d, v: v > 20)
    assert_lines(
        stream.edges_csv_lines(), "2,3,23\n3,4,34\n3,5,35\n4,5,45\n5,1,51"
    )


def test_filter_edges_keep_all():
    stream = long_long_stream().filter_edges(lambda s, d, v: v > 0)
    assert len(stream.collect_edges()) == 7


def test_filter_edges_discard_all():
    # TestFilterEdges discard golden (:86): empty
    stream = long_long_stream().filter_edges(lambda s, d, v: v < 0)
    assert stream.collect_edges() == []


def test_filter_vertices():
    # TestFilterVertices.testWithSimpleFilter (:40-43): keep vertices > 1
    stream = long_long_stream().filter_vertices(lambda v: v > 1)
    assert_lines(stream.edges_csv_lines(), "2,3,23\n3,4,34\n3,5,35\n4,5,45")


def test_filter_vertices_discard_all():
    stream = long_long_stream().filter_vertices(lambda v: v < 0)
    assert stream.collect_edges() == []


def test_reverse():
    # TestReverse.java:38-44
    stream = long_long_stream().reverse()
    assert_lines(
        stream.edges_csv_lines(),
        "2,1,12\n3,1,13\n3,2,23\n4,3,34\n5,3,35\n5,4,45\n1,5,51",
    )


def test_undirected():
    # TestUndirected.java:38-51
    stream = long_long_stream().undirected()
    assert_lines(
        stream.edges_csv_lines(),
        "1,2,12\n2,1,12\n1,3,13\n3,1,13\n2,3,23\n3,2,23\n3,4,34\n4,3,34\n"
        "3,5,35\n5,3,35\n4,5,45\n5,4,45\n5,1,51\n1,5,51",
    )


def test_union():
    # TestUnion.java:41-47: union of two halves restores the full fixture
    a = EdgeStream.from_collection(LONG_LONG_EDGES[:4], CFG)
    b = EdgeStream.from_collection(LONG_LONG_EDGES[4:], CFG)
    assert_lines(
        a.union(b).edges_csv_lines(),
        "1,2,12\n1,3,13\n2,3,23\n3,4,34\n3,5,35\n4,5,45\n5,1,51",
    )


def test_distinct():
    # TestDistinct.java:38-44: duplicated fixture collapses to one copy
    stream = EdgeStream.from_collection(
        LONG_LONG_EDGES + LONG_LONG_EDGES, CFG, batch_size=5
    ).distinct()
    assert_lines(
        stream.edges_csv_lines(),
        "1,2,12\n1,3,13\n2,3,23\n3,4,34\n3,5,35\n4,5,45\n5,1,51",
    )


def test_distinct_within_batch():
    # duplicates inside one micro-batch are also collapsed
    stream = EdgeStream.from_collection(
        [(1, 2, 7), (1, 2, 7), (1, 2, 7), (2, 3, 9)], CFG, batch_size=4
    ).distinct()
    assert_lines(stream.edges_csv_lines(), "1,2,7\n2,3,9")


def test_transformations_batch_size_invariant():
    # The same pipeline over batch sizes 1..7 yields identical edge sets.
    for bs in (1, 2, 3, 7):
        stream = long_long_stream(batch_size=bs).filter_edges(
            lambda s, d, v: v > 20
        )
        assert_lines(
            stream.edges_csv_lines(), "2,3,23\n3,4,34\n3,5,35\n4,5,45\n5,1,51"
        )


def test_epoch_timestamps_fail_loudly():
    """Epoch-ms timestamps exceed int32 and would silently wrap in the
    device cast; the constructor must refuse them (host owns time —
    rebase to stream-relative ms)."""
    import numpy as np
    import pytest

    from gelly_streaming_tpu.core.types import EdgeBatch

    epoch_ms = np.array([1_785_000_000_000], np.int64)
    with pytest.raises(ValueError, match="rebase"):
        EdgeBatch.from_arrays(
            np.array([1], np.int32), np.array([2], np.int32), time=epoch_ms
        )
    # relative times are fine
    b = EdgeBatch.from_arrays(
        np.array([1], np.int32),
        np.array([2], np.int32),
        time=np.array([12345], np.int64),
    )
    assert int(b.time[0]) == 12345


def test_epoch_timestamps_guard_covers_from_edges_and_tracers():
    import jax
    import numpy as np
    import pytest

    from gelly_streaming_tpu.core.types import EdgeBatch

    with pytest.raises(ValueError, match="rebase"):
        EdgeBatch.from_edges(
            [(1, 2, 0.0, 1_785_000_000_000)], with_time=True
        )
    # traced construction stays legal (wire steps build batches inside jit)
    src = np.array([1], np.int32)
    dst = np.array([2], np.int32)

    def build(t):
        return EdgeBatch.from_arrays(src, dst, time=t).time

    out = jax.jit(build)(np.array([7], np.int64))
    assert int(out[0]) == 7


def test_distinct_valued_stream_contract():
    """VERDICT r3 item 8: the reference dedupes the whole Edge INCLUDING
    its value (SimpleEdgeStream.java:309-323).  distinct() now matches it
    for valued streams by default (two same-endpoint edges with different
    values both survive; an exact repeat is dropped), with
    by='endpoints' as the explicit first-value-wins deviation."""
    import pytest

    from gelly_streaming_tpu.core.config import StreamConfig
    from gelly_streaming_tpu.core.stream import EdgeStream

    cfg = StreamConfig(vertex_capacity=16, batch_size=4)
    valued = [(1, 2, 10.0), (1, 2, 20.0), (1, 2, 10.0), (3, 4, 30.0)]
    # default = whole-edge (reference semantics): the exact repeat drops,
    # the different-value edge on the same endpoints survives
    edges = EdgeStream.from_collection(valued, cfg).distinct().collect_edges()
    assert [(s, d, v) for s, d, v in edges] == [
        (1, 2, 10.0),
        (1, 2, 20.0),
        (3, 4, 30.0),
    ]
    # cross-batch memory of (pair, value): repeat in a later batch drops too
    edges2 = (
        EdgeStream.from_collection(valued, cfg, batch_size=2)
        .distinct()
        .collect_edges()
    )
    assert edges2 == edges
    # explicit opt-in: endpoint-pair dedup, first occurrence's value wins
    ep = (
        EdgeStream.from_collection(valued, cfg)
        .distinct(by="endpoints")
        .collect_edges()
    )
    assert [(s, d, v) for s, d, v in ep] == [(1, 2, 10.0), (3, 4, 30.0)]
    with pytest.raises(ValueError, match="unknown distinct mode"):
        EdgeStream.from_collection(valued, cfg).distinct(by="pair")
    # multi-leaf / wide values have no sound dense whole-edge form: loud
    with pytest.raises(ValueError, match="single scalar value"):
        (
            EdgeStream.from_collection(valued, cfg)
            .map_edges(lambda s, d, v: (v, v))
            .distinct()
            .collect_edges()
        )


def test_distinct_value_less_stream_uses_single_table():
    """Known value-less sources resolve auto -> endpoint mode (identical
    semantics, half the state)."""
    import numpy as np

    from gelly_streaming_tpu.core.config import StreamConfig
    from gelly_streaming_tpu.core.stream import EdgeStream, _DistinctStage

    cfg = StreamConfig(vertex_capacity=16, batch_size=4)
    src = np.array([1, 1, 3], np.int32)
    dst = np.array([2, 2, 4], np.int32)
    stream = EdgeStream.from_arrays(src, dst, cfg).distinct()
    stage = stream._stages[-1]
    assert isinstance(stage, _DistinctStage) and stage.mode == "endpoints"
    assert [e[:2] for e in stream.collect_edges()] == [(1, 2), (3, 4)]


def test_distinct_whole_edge_bf16_values_bitcast_exactly():
    """bfloat16 (numpy dtype kind 'V') must hit the BITCAST branch — astype
    truncation would merge genuinely distinct values (review finding)."""
    import jax.numpy as jnp

    from gelly_streaming_tpu.core.config import StreamConfig
    from gelly_streaming_tpu.core.stream import EdgeStream

    cfg = StreamConfig(vertex_capacity=16, batch_size=4)
    stream = (
        EdgeStream.from_collection(
            [(1, 2, 1.5), (1, 2, 1.0), (1, 2, 1.5)], cfg
        )
        .map_edges(lambda s, d, v: v.astype(jnp.bfloat16))
        .distinct()
    )
    edges = stream.collect_edges()
    # 1.5 and 1.0 are distinct bf16 edges; the exact 1.5 repeat drops
    assert len(edges) == 2

"""The packed-wire aggregate fast path must match the simulated runtime.

VERDICT r1 item 2: the product API (EdgeStream.aggregate) rides the packed-wire
+ prefetch ingest (io/wire.py) whenever the source exposes wire arrays.  These
tests pin (a) eligibility gating, (b) result equivalence against the simulated
pane path on CC and bipartiteness, and (c) stage composition (stages run in-jit
after the device-side unpack).
"""

import numpy as np

from gelly_streaming_tpu.core.config import StreamConfig
from gelly_streaming_tpu.core.stream import EdgeStream
from gelly_streaming_tpu.library.bipartiteness import BipartitenessCheck
from gelly_streaming_tpu.library.connected_components import ConnectedComponents


def _random_edges(n=4000, c=64, seed=7):
    rng = np.random.default_rng(seed)
    return (
        rng.integers(0, c, n).astype(np.int32),
        rng.integers(0, c, n).astype(np.int32),
    )


def test_from_arrays_is_wire_eligible():
    src, dst = _random_edges()
    cfg = StreamConfig(vertex_capacity=64, batch_size=256)
    stream = EdgeStream.from_arrays(src, dst, cfg)
    agg = ConnectedComponents()
    assert agg._wire_eligible(stream)
    sharded = StreamConfig(vertex_capacity=64, batch_size=256, num_shards=2)
    assert not agg._wire_eligible(EdgeStream.from_arrays(src, dst, sharded))
    # collection sources have no wire arrays -> simulated path
    coll = EdgeStream.from_collection([(0, 1)], cfg)
    assert not agg._wire_eligible(coll)


def test_wire_cc_matches_simulated():
    src, dst = _random_edges()
    cfg = StreamConfig(vertex_capacity=64, batch_size=256)
    fast = (
        EdgeStream.from_arrays(src, dst, cfg)
        .aggregate(ConnectedComponents())
        .collect()
    )
    slow = (
        EdgeStream.from_collection(list(zip(src.tolist(), dst.tolist())), cfg, 256)
        .aggregate(ConnectedComponents())
        .collect()
    )
    assert len(fast) == len(slow) == 1
    assert fast[0][0].components() == slow[0][0].components()


def test_wire_cc_with_stages_matches_simulated():
    src, dst = _random_edges(n=1000, c=32)
    cfg = StreamConfig(vertex_capacity=32, max_degree=40, batch_size=128)

    def pipeline(stream):
        return (
            stream.filter_edges(lambda s, d, v: s != d)
            .undirected()
            .distinct()
            .aggregate(ConnectedComponents())
            .collect()
        )

    fast = pipeline(EdgeStream.from_arrays(src, dst, cfg))
    slow = pipeline(
        EdgeStream.from_collection(list(zip(src.tolist(), dst.tolist())), cfg, 128)
    )
    assert fast[0][0].components() == slow[0][0].components()


def test_wire_bipartiteness_matches_simulated():
    # an odd cycle makes it non-bipartite; also check the bipartite case
    for edges in ([(0, 1), (1, 2), (2, 0)], [(0, 1), (1, 2), (2, 3)]):
        src = np.array([e[0] for e in edges], np.int32)
        dst = np.array([e[1] for e in edges], np.int32)
        cfg = StreamConfig(vertex_capacity=8, batch_size=4)
        fast = (
            EdgeStream.from_arrays(src, dst, cfg)
            .aggregate(BipartitenessCheck())
            .collect()
        )
        slow = (
            EdgeStream.from_collection(edges, cfg, 4)
            .aggregate(BipartitenessCheck())
            .collect()
        )
        assert str(fast[-1][0]) == str(slow[-1][0])


def test_wire_partial_tail_batch():
    # 1000 edges with batch 256 leaves a 232-edge tail: the padded tail step
    # must fold it with correct masking
    src, dst = _random_edges(n=1000, c=64)
    cfg = StreamConfig(vertex_capacity=64, batch_size=256)
    fast = (
        EdgeStream.from_arrays(src, dst, cfg)
        .aggregate(ConnectedComponents())
        .collect()
    )
    slow = (
        EdgeStream.from_collection(list(zip(src.tolist(), dst.tolist())), cfg, 256)
        .aggregate(ConnectedComponents())
        .collect()
    )
    assert fast[0][0].components() == slow[0][0].components()


def test_wire_path_repeat_run_reuses_cache():
    # OutputStream is re-runnable; the second run must produce the same result
    # (fresh state) and reuse the process-global executable cache
    # (core/compile_cache.py) instead of retracing
    from gelly_streaming_tpu.core import compile_cache

    src, dst = _random_edges(n=512, c=64)
    cfg = StreamConfig(vertex_capacity=64, batch_size=128)
    agg = ConnectedComponents()
    out = EdgeStream.from_arrays(src, dst, cfg).aggregate(agg)
    first = out.collect()
    compile_cache.reset_stats()
    second = out.collect()
    stats = compile_cache.stats()
    assert stats["compiles"] == 0, stats
    assert first[0][0].components() == second[0][0].components()


def test_from_arrays_rejects_out_of_range_ids():
    import pytest

    cfg = StreamConfig(vertex_capacity=1 << 16)
    with pytest.raises(ValueError):
        EdgeStream.from_arrays(np.array([70000]), np.array([1]), cfg)
    # 64-bit ids that would wrap into range after an int32 cast must still fail
    with pytest.raises(ValueError):
        EdgeStream.from_arrays(
            np.array([2**32 + 5], np.int64), np.array([7], np.int64), cfg
        )


def test_wire_ef40_cc_matches_plain():
    # the sorted multiset encoding must reach the same components as plain
    src, dst = _random_edges(n=3000, c=64)
    plain_cfg = StreamConfig(vertex_capacity=64, batch_size=256, wire_encoding="plain")
    ef_cfg = StreamConfig(vertex_capacity=64, batch_size=256, wire_encoding="ef40")
    plain = (
        EdgeStream.from_arrays(src, dst, plain_cfg)
        .aggregate(ConnectedComponents())
        .collect()
    )
    ef = (
        EdgeStream.from_arrays(src, dst, ef_cfg)
        .aggregate(ConnectedComponents())
        .collect()
    )
    assert plain[0][0].components() == ef[0][0].components()


def test_wire_ef40_rejects_order_sensitive_descriptor():
    import pytest

    from gelly_streaming_tpu.core.aggregation import SummaryBulkAggregation

    class OrderSensitive(SummaryBulkAggregation):  # default order_free=False
        def initial_state(self, cfg):
            return np.zeros(())

        def update(self, state, src, dst, val, mask):
            return state

        def combine(self, a, b):
            return a

    src, dst = _random_edges(n=64, c=16)
    cfg = StreamConfig(vertex_capacity=16, batch_size=32, wire_encoding="ef40")
    with pytest.raises(ValueError, match="order-free"):
        EdgeStream.from_arrays(src, dst, cfg).aggregate(OrderSensitive()).collect()


def test_wire_ef40_bipartiteness_matches_plain():
    for edges in ([(0, 1), (1, 2), (2, 0)], [(0, 1), (1, 2), (2, 3)]):
        src = np.array([e[0] for e in edges], np.int32)
        dst = np.array([e[1] for e in edges], np.int32)
        plain = (
            EdgeStream.from_arrays(
                src, dst, StreamConfig(vertex_capacity=8, batch_size=4)
            )
            .aggregate(BipartitenessCheck())
            .collect()
        )
        ef = (
            EdgeStream.from_arrays(
                src,
                dst,
                StreamConfig(vertex_capacity=8, batch_size=4, wire_encoding="ef40"),
            )
            .aggregate(BipartitenessCheck())
            .collect()
        )
        assert str(plain[-1][0]) == str(ef[-1][0])


def _spy_strategies(monkeypatch):
    """Instrument run()'s strategy selection; returns the call log."""
    import gelly_streaming_tpu.core.aggregation as agg_mod

    calls = []
    orig_wire = agg_mod.SummaryAggregation._wire_records
    orig_mesh = agg_mod.MeshAggregationRunner.run
    orig_mesh_wire = agg_mod.MeshAggregationRunner.wire_records

    def spy_wire(self, *a, **k):
        calls.append("wire")
        return orig_wire(self, *a, **k)

    def spy_mesh(self, *a, **k):
        calls.append("mesh")
        return orig_mesh(self, *a, **k)

    def spy_mesh_wire(self, *a, **k):
        calls.append("mesh-wire")
        return orig_mesh_wire(self, *a, **k)

    monkeypatch.setattr(agg_mod.SummaryAggregation, "_wire_records", spy_wire)
    monkeypatch.setattr(agg_mod.MeshAggregationRunner, "run", spy_mesh)
    monkeypatch.setattr(
        agg_mod.MeshAggregationRunner, "wire_records", spy_mesh_wire
    )
    return calls


def test_aggregate_strategy_selection_matrix(monkeypatch):
    """run() picks wire / mesh / simulated correctly, including with
    checkpointing (the wire path no longer opts out)."""
    src, dst = _random_edges(n=128, c=32)
    calls = _spy_strategies(monkeypatch)

    single = StreamConfig(vertex_capacity=32, batch_size=64)
    sharded = StreamConfig(vertex_capacity=32, batch_size=64, num_shards=8)

    EdgeStream.from_arrays(src, dst, single).aggregate(
        ConnectedComponents()
    ).collect()
    assert calls == ["wire"]

    calls.clear()
    # sharded wire-backed streams ride the sharded STREAMING fold (round 4:
    # per-shard donated carries, no per-pane re-fold), not the pane runner
    EdgeStream.from_arrays(src, dst, sharded).aggregate(
        ConnectedComponents()
    ).collect()
    assert calls == ["mesh-wire"]

    calls.clear()
    import tempfile

    with tempfile.TemporaryDirectory() as d:
        EdgeStream.from_arrays(src, dst, single).aggregate(
            ConnectedComponents(), checkpoint_path=f"{d}/ck"
        ).collect()
    assert calls == ["wire"]  # checkpointing stays on the fast path

    calls.clear()
    EdgeStream.from_collection(
        list(zip(src.tolist(), dst.tolist())), single, 64
    ).aggregate(ConnectedComponents()).collect()
    assert calls == []  # simulated path: neither wire nor mesh

    calls.clear()
    # sharded NON-wire streams (collections) still use the pane runner
    EdgeStream.from_collection(
        list(zip(src.tolist(), dst.tolist())), sharded, 64
    ).aggregate(ConnectedComponents()).collect()
    assert calls == ["mesh"]


def test_aggregate_strategy_selection_replay(monkeypatch):
    """from_wire replay streams select the same strategies as from_arrays:
    wire fast path single-shard (with or without checkpointing), mesh when
    sharded."""
    import tempfile

    from gelly_streaming_tpu.io import wire as wire_mod

    src, dst = _random_edges(n=128, c=32)
    calls = _spy_strategies(monkeypatch)
    bufs, tail = wire_mod.pack_stream(src, dst, 64, 2)
    single = StreamConfig(vertex_capacity=32, batch_size=64)
    sharded = StreamConfig(vertex_capacity=32, batch_size=64, num_shards=8)

    EdgeStream.from_wire(bufs, 64, 2, single, tail=tail).aggregate(
        ConnectedComponents()
    ).collect()
    assert calls == ["wire"]

    calls.clear()
    with tempfile.TemporaryDirectory() as d:
        EdgeStream.from_wire(bufs, 64, 2, single, tail=tail).aggregate(
            ConnectedComponents(), checkpoint_path=f"{d}/ck"
        ).collect()
    assert calls == ["wire"]

    calls.clear()
    EdgeStream.from_wire(bufs, 64, 2, sharded, tail=tail).aggregate(
        ConnectedComponents()
    ).collect()
    assert calls == ["mesh-wire"]  # round 4: sharded streaming wire fold

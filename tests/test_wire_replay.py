"""The wire-replay source (EdgeStream.from_wire).

The reference's hot operator consumes records the upstream network stack
already serialized (SummaryBulkAggregation.java:76-83 behind Flink's Netty
shuffle) — serialization is the producer's cost.  ``from_wire`` is the TPU
analog: the stream arrives as per-batch wire buffers and the fast path's
timed loop is transfer + on-device unpack + fold only.  These tests pin:

* producer/consumer round trip for every encoding (pack_stream -> host decode)
* aggregate() parity: replay == from_arrays, for PAIR40, EF40 and byte widths
* the non-fast-path view (windowed/record consumers see real EdgeBatches)
* EF40 replay refused for order-sensitive aggregations
* positional checkpoints compose with replay (crash + resume equivalence)
* buffer-size validation errors
"""

import numpy as np
import pytest

from gelly_streaming_tpu.core.config import StreamConfig
from gelly_streaming_tpu.core.stream import EdgeStream
from gelly_streaming_tpu.io import wire
from gelly_streaming_tpu.library.connected_components import ConnectedComponents

from gelly_streaming_tpu.ops import unionfind as uf

from fixtures import host_min_labels


def _edges(n, c, seed=0):
    rng = np.random.default_rng(seed)
    return (
        rng.integers(0, c, n).astype(np.int32),
        rng.integers(0, c, n).astype(np.int32),
    )


@pytest.mark.parametrize(
    "capacity,width",
    [
        (128, 2),
        (128, wire.PAIR40),
        (128, (wire.EF40, 128)),
        (1 << 17, 3),
    ],
)
def test_pack_stream_host_roundtrip(capacity, width):
    src, dst = _edges(1000, capacity, seed=3)
    bufs, tail = wire.pack_stream(src, dst, 256, width)
    assert len(bufs) == 3
    assert tail is not None and len(tail[0]) == 1000 - 768
    got_s, got_d = [], []
    for b in bufs:
        s, d = wire.unpack_edges_host(b, 256, width)
        got_s.append(s)
        got_d.append(d)
    got_s, got_d = np.concatenate(got_s), np.concatenate(got_d)
    want_s, want_d = src[:768], dst[:768]
    if isinstance(width, tuple):  # EF40 ships per-batch multisets
        for k in range(3):
            sl = slice(k * 256, (k + 1) * 256)
            assert sorted(zip(got_s[sl], got_d[sl])) == sorted(
                zip(want_s[sl], want_d[sl])
            )
    else:
        assert np.array_equal(got_s, want_s)
        assert np.array_equal(got_d, want_d)


@pytest.mark.parametrize(
    "width", [2, wire.PAIR40, (wire.EF40, 512), 3]
)
def test_replay_aggregate_matches_from_arrays(width):
    capacity = 512
    src, dst = _edges(3000, capacity, seed=7)
    cfg = StreamConfig(vertex_capacity=capacity, batch_size=512)
    bufs, tail = wire.pack_stream(src, dst, 512, width)
    agg = ConnectedComponents()
    replay = EdgeStream.from_wire(bufs, 512, width, cfg, tail=tail)
    assert agg._wire_eligible(replay)
    import jax

    out = replay.aggregate(ConnectedComponents()).collect()
    base = EdgeStream.from_arrays(src, dst, cfg).aggregate(ConnectedComponents())
    expect = base.collect()
    got = np.asarray(jax.jit(uf.compress)(out[-1][0].parent))
    assert np.array_equal(
        got, np.asarray(jax.jit(uf.compress)(expect[-1][0].parent))
    )
    assert np.array_equal(got, host_min_labels(capacity, src, dst))


@pytest.mark.parametrize(
    "width", [2, wire.PAIR40, (wire.EF40, 300), 3]
)
def test_host_decode_equals_device_decode(width):
    """The replay slow path (host numpy decode) and the fused fast path
    (device decode) must read identical edges from one buffer — the guard
    that keeps the two decoders from drifting (EF40's device form is a jax
    scatter and cannot share code with the host flatnonzero form)."""
    import jax

    n, capacity = 501, 300
    src, dst = _edges(n, capacity, seed=9)
    buf = wire.pack_edges(src, dst, width)
    hs, hd = wire.unpack_edges_host(buf, n, width)
    ds, dd = jax.jit(lambda b: wire.unpack_edges(b, n, width))(buf)
    assert np.array_equal(hs, np.asarray(ds))
    assert np.array_equal(hd, np.asarray(dd))


def test_replay_slow_path_sees_edge_batches():
    capacity = 256
    src, dst = _edges(700, capacity, seed=1)
    cfg = StreamConfig(vertex_capacity=capacity, batch_size=128)
    bufs, tail = wire.pack_stream(src, dst, 128, wire.PAIR40)
    stream = EdgeStream.from_wire(bufs, 128, wire.PAIR40, cfg, tail=tail)
    # a record-plane consumer (degrees) walks the factory, not the wire path
    got = dict(stream.get_degrees().collect())
    deg = np.zeros(capacity, np.int64)
    for a, b in zip(src, dst):
        deg[a] += 1
        deg[b] += 1
    # degrees() emits a running per-vertex trace; the last record per vertex
    # carries its final degree
    expect = {int(v): int(deg[v]) for v in np.union1d(src, dst)}
    assert got == expect


def test_ef40_replay_refused_for_order_sensitive_fold():
    capacity = 128
    src, dst = _edges(256, capacity)
    cfg = StreamConfig(vertex_capacity=capacity, batch_size=128)
    width = (wire.EF40, capacity)
    bufs, tail = wire.pack_stream(src, dst, 128, width)
    stream = EdgeStream.from_wire(bufs, 128, width, cfg, tail=tail)

    from gelly_streaming_tpu.core.aggregation import SummaryAggregation

    class LastEdge(SummaryAggregation):
        order_free = False

        def initial_state(self, cfg):
            import jax.numpy as jnp

            return jnp.zeros((2,), jnp.int32)

        def update(self, state, src, dst, val, mask):
            import jax.numpy as jnp

            idx = jnp.where(mask.any(), jnp.argmax(jnp.cumsum(mask)), 0)
            return jnp.stack([src[idx], dst[idx]])

    with pytest.raises(ValueError, match="order-free"):
        stream.aggregate(LastEdge()).collect()


def test_from_wire_validates_buffer_sizes():
    cfg = StreamConfig(vertex_capacity=128, batch_size=64)
    with pytest.raises(ValueError, match="bytes"):
        EdgeStream.from_wire([np.zeros(7, np.uint8)], 64, 2, cfg)
    with pytest.raises(ValueError, match="tail"):
        bufs, _ = wire.pack_stream(*_edges(64, 128), 64, 2)
        EdgeStream.from_wire(
            bufs, 64, 2, cfg, tail=(np.zeros(64, np.int32), np.zeros(64, np.int32))
        )


def test_replay_checkpoint_crash_resume(tmp_path, monkeypatch):
    capacity = 128
    src, dst = _edges(2048, capacity, seed=5)
    cfg = StreamConfig(
        vertex_capacity=capacity, batch_size=64, wire_checkpoint_batches=4
    )
    width = (wire.EF40, capacity)
    bufs, tail = wire.pack_stream(src, dst, 64, width)
    path = str(tmp_path / "ck")

    clean = (
        EdgeStream.from_wire(bufs, 64, width, cfg)
        .aggregate(ConnectedComponents())
        .collect()
    )

    import gelly_streaming_tpu.utils.checkpoint as ckpt

    real_save = ckpt.save_state
    saves = []

    class _Crash(RuntimeError):
        pass

    def crashing_save(p, state):
        real_save(p, state)
        saves.append(1)
        if len(saves) == 3:
            raise _Crash()

    monkeypatch.setattr(ckpt, "save_state", crashing_save)
    stream = EdgeStream.from_wire(bufs, 64, width, cfg)
    with pytest.raises(_Crash):
        stream.aggregate(ConnectedComponents(), checkpoint_path=path).collect()
    monkeypatch.setattr(ckpt, "save_state", real_save)

    resumed = (
        EdgeStream.from_wire(bufs, 64, width, cfg)
        .aggregate(ConnectedComponents(), checkpoint_path=path)
        .collect()
    )
    import jax

    assert np.array_equal(
        np.asarray(jax.jit(uf.compress)(resumed[-1][0].parent)),
        np.asarray(jax.jit(uf.compress)(clean[-1][0].parent)),
    )


def test_replay_on_the_mesh_path():
    """A replay stream with num_shards > 1 is not wire-eligible (the fast
    path is single-partition); it must flow through the mesh runner via the
    host decode and still produce exact labels."""
    capacity = 1 << 10
    src, dst = _edges(4096, capacity, seed=11)
    cfg = StreamConfig(vertex_capacity=capacity, batch_size=1024, num_shards=4)
    width = (wire.EF40, capacity)
    bufs, tail = wire.pack_stream(src, dst, 1024, width)
    stream = EdgeStream.from_wire(bufs, 1024, width, cfg, tail=tail)
    agg = ConnectedComponents()
    assert not agg._wire_eligible(stream)
    import jax

    out = stream.aggregate(agg).collect()
    got = np.asarray(jax.jit(uf.compress)(out[-1][0].parent))
    assert np.array_equal(got, host_min_labels(capacity, src, dst))


def test_replay_feeds_block_sharded_cc():
    """The O(C/S) block-distributed CC plane consumes a wire-replay stream
    (panes come from the factory's host decode) and still matches the host
    union-find exactly — replay composes with the scale-out label plane."""
    from gelly_streaming_tpu.library.connected_components import (
        BlockShardedCC,
        unshard_labels,
    )

    capacity = 1 << 10
    src, dst = _edges(3000, capacity, seed=21)
    cfg = StreamConfig(vertex_capacity=capacity, batch_size=512)
    width = (wire.EF40, capacity)
    bufs, tail = wire.pack_stream(src, dst, 512, width)
    stream = EdgeStream.from_wire(bufs, 512, width, cfg, tail=tail)
    outs = list(BlockShardedCC().run(stream))
    labels = unshard_labels(outs[-1][0])
    assert np.array_equal(labels, host_min_labels(capacity, src, dst))


def test_from_wire_bounds_checks_ids():
    """Out-of-range vertex ids must fail loudly at construction (advisor r3
    medium): EF40 widths wider than the config are refused outright; fixed
    widths get the first buffer decoded as a smoke guard; tail ids are
    always checked."""
    import numpy as np
    import pytest

    from gelly_streaming_tpu.core.config import StreamConfig
    from gelly_streaming_tpu.core.stream import EdgeStream
    from gelly_streaming_tpu.io import wire

    cfg = StreamConfig(vertex_capacity=64, batch_size=8)
    # EF40 capacity beyond cfg.vertex_capacity: refused without decoding
    with pytest.raises(ValueError, match="EF40 width capacity"):
        EdgeStream.from_wire([], 8, (wire.EF40, 1 << 20), cfg)
    # fixed width whose id range exceeds capacity: first buffer smoke-checked
    bad = wire.pack_edges(
        np.array([70] * 8, np.int32), np.array([1] * 8, np.int32), 2
    )
    with pytest.raises(ValueError, match="decodes vertex ids"):
        EdgeStream.from_wire([bad], 8, 2, cfg)
    ok = wire.pack_edges(
        np.array([63] * 8, np.int32), np.array([1] * 8, np.int32), 2
    )
    EdgeStream.from_wire([ok], 8, 2, cfg)  # in-range ids pass
    # tail ids always checked (raw arrays, cheap)
    with pytest.raises(ValueError, match="tail vertex ids"):
        EdgeStream.from_wire(
            [ok], 8, 2, cfg,
            tail=(np.array([99], np.int32), np.array([1], np.int32)),
        )

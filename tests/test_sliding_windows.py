"""Sliding-window slice(): pane-shared windows beyond the tumbling-only
reference (SimpleEdgeStream.java:135-167 exposes only timeWindow(size); Flink
itself offers timeWindow(size, slide) one call below — this is the framework's
native equivalent, built from core/windows.sliding_panes).

Semantics pinned here: window w covers panes [w-k+1, w] (k = size // slide),
fires when pane w closes, partial early windows fire, empty windows do not,
and the trailing k-1 windows flush at end-of-stream.
"""

import numpy as np
import pytest

from gelly_streaming_tpu.core.config import StreamConfig
from gelly_streaming_tpu.core.stream import EdgeStream
from gelly_streaming_tpu.core.types import EdgeDirection
from gelly_streaming_tpu.core.windows import WindowPane, sliding_panes


def _pane(wid, edges, slide=1000):
    src = np.array([e[0] for e in edges], np.int32)
    dst = np.array([e[1] for e in edges], np.int32)
    max_ts = (wid + 1) * slide - 1 if wid >= 0 else -1
    return WindowPane(wid, max_ts, src, dst, None, None)


def _ids(pane):
    return sorted(zip(pane.src.tolist(), pane.dst.tolist()))


# ---------------------------------------------------------------------------
# unit: sliding_panes


def test_sliding_windows_share_panes():
    panes = [_pane(0, [(1, 2)]), _pane(1, [(3, 4)]), _pane(2, [(5, 6)])]
    out = list(sliding_panes(iter(panes), 2, 1000))
    # windows: 0:{p0} (partial early), 1:{p0,p1}, 2:{p1,p2}, trailing 3:{p2}
    assert [w.window_id for w in out] == [0, 1, 2, 3]
    assert _ids(out[0]) == [(1, 2)]
    assert _ids(out[1]) == [(1, 2), (3, 4)]
    assert _ids(out[2]) == [(3, 4), (5, 6)]
    assert _ids(out[3]) == [(5, 6)]
    # window end timestamps advance by the slide
    assert [w.max_timestamp for w in out] == [999, 1999, 2999, 3999]


def test_sliding_windows_skip_empty_gaps():
    panes = [_pane(0, [(1, 2)]), _pane(5, [(7, 8)])]
    out = list(sliding_panes(iter(panes), 3, 1000))
    # pane 0 is in windows 0-2; panes 1-4 are empty so windows 3-4 never
    # fire; pane 5 is in windows 5-7
    assert [w.window_id for w in out] == [0, 1, 2, 5, 6, 7]
    assert all(_ids(w) == [(1, 2)] for w in out[:3])
    assert all(_ids(w) == [(7, 8)] for w in out[3:])


def test_sliding_k1_and_untimed_pass_through():
    panes = [_pane(0, [(1, 2)]), _pane(1, [(3, 4)])]
    assert list(sliding_panes(iter(panes), 1, 1000)) == panes
    untimed = [_pane(-1, [(1, 2)])]
    assert list(sliding_panes(iter(untimed), 4, 1000)) == untimed


def test_sliding_windows_bounded_cache():
    # only k panes may be cached at once, whatever the stream length
    import itertools

    def gen():
        for w in itertools.count():
            yield _pane(w, [(w, w + 1)])

    out = sliding_panes(gen(), 4, 1000)
    for _ in range(100):
        next(out)
    # windows past the warmup each hold exactly k panes' edges
    w = next(out)
    assert w.num_edges == 4


# ---------------------------------------------------------------------------
# integration: slice(window, slide) through reduce_on_edges, differentially
# against a per-window host recompute


TIMED_EDGES = [
    # (src, dst, val, t_ms) — panes of 1000 ms: t//1000 in {0, 0, 1, 2, 4}
    (1, 2, 10, 100),
    (3, 1, 7, 900),
    (1, 4, 5, 1500),
    (2, 3, 20, 2400),
    (4, 1, 2, 4700),
]


def _host_windows(k):
    """Expected (vid, sum) records across all fired sliding windows."""
    pane_of = {i: e[3] // 1000 for i, e in enumerate(TIMED_EDGES)}
    first, last = min(pane_of.values()), max(pane_of.values())
    recs = []
    for wid in range(first, last + k):
        sums = {}
        for i, (s, _, v, _) in enumerate(TIMED_EDGES):
            if wid - k + 1 <= pane_of[i] <= wid:
                sums[s] = sums.get(s, 0) + v
        recs.extend(sums.items())
    return sorted(recs)


@pytest.mark.parametrize("window,slide,k", [(2000, 1000, 2), (3000, 1000, 3)])
def test_slice_sliding_reduce_matches_host(window, slide, k):
    cfg = StreamConfig(vertex_capacity=16, max_degree=16, batch_size=2)
    stream = EdgeStream.from_collection(
        TIMED_EDGES, cfg, batch_size=2, with_time=True
    )
    out = stream.slice(window, EdgeDirection.OUT, slide_ms=slide).reduce_on_edges(
        lambda a, b: a + b
    )
    assert sorted(tuple(r) for r in out.collect()) == _host_windows(k)


def test_slice_slide_equal_window_is_tumbling():
    cfg = StreamConfig(vertex_capacity=16, max_degree=16, batch_size=2)

    def run(**kw):
        return sorted(
            tuple(r)
            for r in EdgeStream.from_collection(
                TIMED_EDGES, cfg, batch_size=2, with_time=True
            )
            .slice(2000, EdgeDirection.OUT, **kw)
            .reduce_on_edges(lambda a, b: a + b)
            .collect()
        )

    assert run(slide_ms=2000) == run()


def test_slice_sliding_validation():
    cfg = StreamConfig(vertex_capacity=16, max_degree=16, batch_size=2)
    stream = EdgeStream.from_collection(TIMED_EDGES, cfg, with_time=True)
    with pytest.raises(ValueError, match="multiple"):
        stream.slice(2000, EdgeDirection.OUT, slide_ms=1500)
    with pytest.raises(ValueError, match="slide_ms"):
        stream.slice(2000, EdgeDirection.OUT, slide_ms=0)
    with pytest.raises(ValueError, match="slide_ms"):
        stream.slice(2000, EdgeDirection.OUT, slide_ms=3000)


def test_slice_sliding_sharded_matches_single():
    """The mesh path shares _panes(): sliding windows must agree with the
    single-device kernel over the 8-device mesh."""
    single = StreamConfig(vertex_capacity=16, max_degree=16, batch_size=2)
    sharded = StreamConfig(
        vertex_capacity=16, max_degree=16, batch_size=2, num_shards=8
    )

    def run(cfg):
        return sorted(
            tuple(r)
            for r in EdgeStream.from_collection(
                TIMED_EDGES, cfg, batch_size=2, with_time=True
            )
            .slice(2000, EdgeDirection.OUT, slide_ms=1000)
            .reduce_on_edges(lambda a, b: a + b)
            .collect()
        )

    assert run(sharded) == run(single)


def test_window_triangles_sliding():
    """Sliding triangle counts: each window's count equals a host recount of
    the union of its panes (WindowTriangles semantics over sliding panes)."""
    from gelly_streaming_tpu.library.triangles import window_triangles

    edges = [
        # pane 0: triangle 1-2-3; pane 1: edges 3-4, 4-5; pane 2: 3-5
        (1, 2, 0, 100),
        (2, 3, 0, 200),
        (1, 3, 0, 300),
        (3, 4, 0, 1100),
        (4, 5, 0, 1200),
        (3, 5, 0, 2100),
    ]
    cfg = StreamConfig(vertex_capacity=16, max_degree=16, batch_size=2)

    def host_count(pane_ids):
        es = {
            frozenset((s, d))
            for s, d, _, t in edges
            if t // 1000 in pane_ids
        }
        vs = sorted({v for e in es for v in e})
        cnt = 0
        for i, a in enumerate(vs):
            for b in vs[i + 1 :]:
                for c in vs[vs.index(b) + 1 :]:
                    if (
                        frozenset((a, b)) in es
                        and frozenset((b, c)) in es
                        and frozenset((a, c)) in es
                    ):
                        cnt += 1
        return cnt

    stream = EdgeStream.from_collection(edges, cfg, batch_size=2, with_time=True)
    got = window_triangles(stream, 2000, slide_ms=1000).collect()
    # windows: 0:{p0} 1:{p0,p1} 2:{p1,p2} trailing 3:{p2}
    want = [
        host_count({0}),
        host_count({0, 1}),
        host_count({1, 2}),
        host_count({2}),
    ]
    assert [c for c, _ in got] == want
    # window 2 closes the 3-4-5 triangle across panes 1+2
    assert want == [1, 1, 1, 0]
    with pytest.raises(ValueError, match="multiple"):
        window_triangles(stream, 2000, slide_ms=1500)


@pytest.mark.parametrize("seed", [0, 1, 2])
def test_slice_sliding_randomized_differential(seed):
    """Random timed streams, random k: sliding reduce records must equal the
    brute-force per-window host recompute."""
    rng = np.random.default_rng(seed)
    n = int(rng.integers(10, 40))
    edges = [
        (
            int(rng.integers(1, 8)),
            int(rng.integers(1, 8)),
            int(rng.integers(1, 100)),
            int(rng.integers(0, 9000)),
        )
        for _ in range(n)
    ]
    edges.sort(key=lambda e: e[3])  # ascending event time
    k = int(rng.integers(2, 5))
    slide = 1000
    cfg = StreamConfig(vertex_capacity=16, max_degree=64, batch_size=4)
    out = (
        EdgeStream.from_collection(edges, cfg, batch_size=4, with_time=True)
        .slice(k * slide, EdgeDirection.OUT, slide_ms=slide)
        .reduce_on_edges(lambda a, b: a + b)
    )
    got = sorted(tuple(r) for r in out.collect())

    pane_ids = sorted({e[3] // slide for e in edges})
    want = []
    for wid in range(pane_ids[0], pane_ids[-1] + k):
        sums = {}
        for s, _, v, t in edges:
            if wid - k + 1 <= t // slide <= wid:
                sums[s] = sums.get(s, 0) + v
        want.extend(sums.items())
    assert got == sorted(want), (k, edges)


def test_sliding_rejected_on_ingestion_mode_streams():
    cfg = StreamConfig(
        vertex_capacity=16, max_degree=16, batch_size=2, ingest_window_edges=4
    )
    stream = EdgeStream.from_collection([(1, 2), (2, 3)], cfg)
    with pytest.raises(ValueError, match="ingestion-time"):
        stream.slice(2000, EdgeDirection.OUT, slide_ms=1000).reduce_on_edges(
            lambda a, b: a + b
        ).collect()

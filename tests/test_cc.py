"""Streaming Connected Components tests.

Mirrors example/test/ConnectedComponentsTest.java (expected components at :41)
and adds: tree-combine equivalence, multi-window running merge, and the
sharded mesh data plane on the virtual 8-device CPU mesh (the MiniCluster
analog)."""

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec as P

from gelly_streaming_tpu.core.config import StreamConfig
from gelly_streaming_tpu.core.stream import EdgeStream
from gelly_streaming_tpu.library.connected_components import (
    ConnectedComponents,
    ConnectedComponentsTree,
    sharded_cc_fixpoint,
)
from gelly_streaming_tpu.ops import unionfind as uf
from gelly_streaming_tpu.parallel.mesh import make_mesh, shard_map
from gelly_streaming_tpu.parallel.routing import host_route

CC_EDGES = [
    (1, 2),
    (1, 3),
    (2, 3),
    (1, 5),
    (6, 7),
    (8, 9),
]  # ConnectedComponentsTest.java:55-63

CFG = StreamConfig(vertex_capacity=16, max_degree=16)


def test_connected_components_golden():
    stream = EdgeStream.from_collection(CC_EDGES, CFG)
    results = stream.aggregate(ConnectedComponents(window_ms=5)).collect()
    ds = results[-1][0]
    # expected components (ConnectedComponentsTest.java:41)
    assert str(ds) == "{1=[1, 2, 3, 5], 6=[6, 7], 8=[8, 9]}"
    comps = sorted(
        ", ".join(str(v) for v in members)
        for members in ds.components().values()
    )
    assert comps == ["1, 2, 3, 5", "6, 7", "8, 9"]


def test_connected_components_tree_equivalent():
    stream = EdgeStream.from_collection(CC_EDGES, CFG)
    results = stream.aggregate(ConnectedComponentsTree(window_ms=5)).collect()
    assert str(results[-1][0]) == "{1=[1, 2, 3, 5], 6=[6, 7], 8=[8, 9]}"


def test_connected_components_multi_window_merge():
    # Event-time stream spanning three windows: the running summary merges
    # across windows (Merger semantics, SummaryAggregation.java:107-119).
    edges = [
        (1, 2, 0, 10),
        (3, 4, 0, 20),  # window 0: {1,2} {3,4}
        (2, 3, 0, 110),  # window 1 bridges -> {1,2,3,4}
        (5, 6, 0, 210),  # window 2 adds {5,6}
    ]
    stream = EdgeStream.from_collection(edges, CFG, batch_size=1, with_time=True)
    results = stream.aggregate(ConnectedComponents(window_ms=100)).collect()
    assert len(results) == 3
    assert str(results[0][0]) == "{1=[1, 2], 3=[3, 4]}"
    assert str(results[1][0]) == "{1=[1, 2, 3, 4]}"
    assert str(results[2][0]) == "{1=[1, 2, 3, 4], 5=[5, 6]}"


def test_sharded_cc_matches_single_device():
    rng = np.random.default_rng(3)
    c = 256
    m = 400
    src = rng.integers(0, c, m).astype(np.int32)
    dst = rng.integers(0, c, m).astype(np.int32)

    single = np.asarray(
        uf.union_edges(uf.init_parent(c), jnp.asarray(src), jnp.asarray(dst))
    )

    mesh = make_mesh(8)
    routed = host_route(src, dst, 8, key="src")
    fixpoint = jax.jit(
        shard_map(
            lambda p, s, d, k: sharded_cc_fixpoint(
                p, s.reshape(-1), d.reshape(-1), k.reshape(-1)
            ),
            mesh=mesh,
            in_specs=(P(), P("shards"), P("shards"), P("shards")),
            out_specs=P(),
        )
    )
    parent = fixpoint(
        uf.init_parent(c),
        jnp.asarray(routed.src),
        jnp.asarray(routed.dst),
        jnp.asarray(routed.mask),
    )
    np.testing.assert_array_equal(np.asarray(parent), single)

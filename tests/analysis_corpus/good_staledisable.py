"""Clean counterpart: a suppression that still earns its keep.

The raw jit here is deliberate (a cold diagnostic probe outside the hot
dispatch plane), the disable comment silences a LIVE RAWJIT finding, so
the stale-disable post-check leaves it alone.

Expected findings: none.  Analyzer input only — never imported.
"""

import jax

# cold path: a one-shot self-test probe, never re-created per stream
probe = jax.jit(lambda x: x)  # graft: disable=RAWJIT — cold diagnostic probe

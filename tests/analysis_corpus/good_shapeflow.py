"""Clean counterpart: every shape at a compile boundary is CONST or
pow2-BUCKETED, every closed-over value is in the key, and no bare scalar
crosses a cached kernel boundary.

Expected findings: none.  Imported by tests/test_shapeflow.py: the
runtime cross-check drives ``bucketed_step`` over the same batch sizes
as the bad twin's ``unbucketed_step`` and asserts zero recompiles.
"""

import numpy as np

from gelly_streaming_tpu.core import compile_cache


def pow2_bucket(n):
    """Next power of two >= n (>= 1): the shape-class rounding that keeps
    successive panes on one executable."""
    return 1 << max(int(n) - 1, 0).bit_length()


def _build_fold():
    import jax.numpy as jnp

    def fold(x):
        return jnp.sum(x)

    return fold


def bucketed_step(values):
    live = [v for v in values if v > 0.0]
    cap = pow2_bucket(max(len(live), 1))
    fn = compile_cache.cached_jit(("good_fold", cap), _build_fold)
    import jax.numpy as jnp

    return fn(jnp.zeros((cap,), jnp.float32))


def _fold_for(n):
    return compile_cache.cached_jit(("good_interp_fold", n), _build_fold)


def interp_step(v):
    # the unique-count is rounded through the bucket helper BEFORE it
    # reaches the callee's key
    return _fold_for(pow2_bucket(len(np.unique(v))))


def make_scaled_fold(scale):
    def build():
        import jax.numpy as jnp

        def fold(x):
            return jnp.sum(x) * scale

        return fold

    # scale is in the key: distinct scales get distinct entries
    return compile_cache.cached_jit(("good_scaled_fold", scale), build)


def _build_scaled():
    import jax.numpy as jnp

    def fold(x, s):
        return jnp.sum(x) * s

    return fold


_drift_fold = compile_cache.cached_jit(("good_drift_fold",), _build_scaled)


def drift_step(x):
    import jax.numpy as jnp

    # dtype pinned at the call site: no weak-type fork
    return _drift_fold(x, jnp.float32(0.5))

"""Clean counterpart: every guarded access holds its lock, and a provably
single-threaded reader carries the '# single-thread:' marker.

Expected findings: none.  Analyzer input only — never imported.
"""

import threading

_LOCK = threading.Lock()
_COUNT = 0  # guarded-by: _LOCK


def bump():
    global _COUNT
    with _LOCK:
        _COUNT += 1


def report():  # single-thread: read at teardown, after every worker joined
    return _COUNT


class Meter:
    def __init__(self):
        self._lock = threading.Lock()
        self.total = 0  # guarded-by: _lock

    def add(self, n):
        with self._lock:
            self.total += n

    def snapshot(self):
        with self._lock:
            return self.total

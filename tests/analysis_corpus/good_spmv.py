"""Good twin of bad_spmv.py: the per-iteration direction pick is a
branchless ``lax.cond`` on the traced density (one executable serves both
lowerings; force modes fold into the threshold scalar), and the dispatch
loop keeps results on device — the single drain sync sits after the
region's end, allowlisted where the protocol requires it.

Expected findings: none.  Analyzer input only — never imported.
"""

import jax
import jax.numpy as jnp
import numpy as np

from gelly_streaming_tpu.core import compile_cache

CAPACITY = 1024


def make():
    def step(d_src, d_w, d_msk, x, fm, thr):
        def pull(x):
            cand = jnp.where(d_msk, x[d_src] + d_w, jnp.float32(1e30))
            return jnp.minimum(x, cand[:CAPACITY])

        dens = jnp.sum(fm).astype(jnp.float32) / CAPACITY
        return jax.lax.cond(dens > thr, pull, lambda x: x, x)

    return step


step = compile_cache.cached_jit(("corpus_spmv_step_good",), make)


def drive(panes, x, fm, thr):
    dists = []
    # hot-loop: per-window direction-optimized dispatch
    for pane in panes:
        x = step(pane.d_src, pane.d_w, pane.d_msk, x, fm, thr)
        dists.append(x)  # stays on device; drained once below
    # hot-loop-end
    return [np.asarray(d) for d in dists]

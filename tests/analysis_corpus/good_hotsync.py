"""Clean counterpart: the one sanctioned sync is allowlisted — on the
CLOSING line of a multi-line call (the satellite regression: the marker
must be honored on any physical line of the call, not just its first).

Expected findings: none.  Analyzer input only — never imported.
"""

import numpy as np


def drain(xs):
    out = []
    # hot-loop: dispatch loop
    for x in xs:
        out.append(
            np.asarray(
                x
            )  # hot-loop-ok: completion-queue drain, the sanctioned sync
        )
    # hot-loop-end
    return out

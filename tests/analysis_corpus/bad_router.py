"""Seeded bugs: the fleet tier's lock discipline broken both ways (ISSUE
20).  The router's placement pin table and the relay set are
'# guarded-by:' their locks yet mutated bare, and the failover path
(registry lock, then via ``_redirect`` the placement lock) inverts the
order the placement path takes (placement lock, then via
``_probe_alive`` the registry lock) — no single function acquires both,
so only the interprocedural propagation can see the cycle.

Expected findings: exactly two UNGUARDED (the module pin table and the
instance relay set) and one LOCKORDER naming the
_REGISTRY->_PLACEMENT->_REGISTRY cycle.  Analyzer input only — never
imported.
"""

import threading

_REGISTRY = threading.Lock()
_PLACEMENT = threading.Lock()

_ALIVE = {}  # guarded-by: _REGISTRY
_PINS = {}  # guarded-by: _PLACEMENT


def pin(key, backend):
    _PINS[key] = backend  # BUG: races place() reading the table under lock


def failover(name, standby):
    with _REGISTRY:
        _ALIVE[name] = False
        _redirect(name, standby)


def _redirect(name, standby):
    with _PLACEMENT:
        _PINS[name] = standby


def place(key):
    with _PLACEMENT:
        backend = _PINS.get(key)
        return backend if _probe_alive(backend) else None


def _probe_alive(backend):
    with _REGISTRY:
        return _ALIVE.get(backend, False)


class Router:
    def __init__(self):
        self._lock = threading.Lock()
        self._relays = set()  # guarded-by: _lock

    def attach(self, relay):
        self._relays.add(relay)  # BUG: races stop() snapshotting the set

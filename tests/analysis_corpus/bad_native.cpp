// Seeded-defect twin for the nativecheck pass family (#10-#13): every
// finding below is asserted by exact code in tests/test_analysis.py, so a
// checker that finds nothing anywhere fails there instead of passing
// vacuously.  The shapes mirror the real native tree: a ctypes export
// drifting from utils/native.py, untrusted socket bytes read before any
// size check, narrow size arithmetic, and an early return that leaks.
#include <cstdint>
#include <cstdlib>
#include <cstring>

extern "C" {

// NATIVEABI (arity): utils/native.py declares count_rows(path) — the
// extra flag pushes a frame ctypes never marshals
int64_t count_rows(const char* path, int64_t bogus_flag) {
  (void)path;
  return bogus_flag;
}

// NATIVEABI (width): capacity is int32 in NATIVE_SIGNATURES; int64 here
// reads 4 bytes of stack garbage into the upper half
int64_t cc_baseline(const int32_t* src, const int32_t* dst, int64_t n,
                    int32_t* parent, int64_t capacity) {
  (void)src;
  (void)dst;
  (void)parent;
  (void)capacity;
  return n;
}

// NATIVEABI (unlisted): an export with no ctypes row is a C ABI nobody
// declared — the first Python caller to guess the signature corrupts it.
// The body seeds the three memory rules:
// untrusted: buf[nbytes]
int64_t decode_probe(const uint8_t* buf, int64_t nbytes, int64_t n,
                     int32_t* out) {
  int32_t* tmp = static_cast<int32_t*>(malloc((n + 1) * 4));  // NATIVEOVFL
  if (!tmp) return -4;  // exempt: the allocation's own failure guard
  for (int64_t i = 0; i < n; ++i) {
    tmp[i] = buf[2 * i];  // NATIVEBOUND: no comparison against nbytes ran
  }
  if (tmp[0] < 0) return -2;  // NATIVELEAK: refusal path drops tmp
  memcpy(out, tmp, n * 4);  // NATIVEOVFL: narrow arithmetic again
  free(tmp);
  return n;
}

}  // extern "C"

"""Clean counterpart of bad_wirebin.py: the decoder dispatch loop keeps
every host sync out of the '# hot-loop' region, and the bin-arena wire
counters only move under their lock.

Expected findings: none.  Analyzer input only — never imported.
"""

import threading

import numpy as np

_WIRE_LOCK = threading.Lock()
_WIRE_BYTES = 0  # guarded-by: _WIRE_LOCK


def record_shipped(nbytes):
    global _WIRE_BYTES
    with _WIRE_LOCK:
        _WIRE_BYTES += nbytes


def dispatch_compressed(bufs, fold, carry):
    # hot-loop: compressed wire dispatch (decode fuses into the fold)
    for buf in bufs:
        record_shipped(buf.nbytes)
        carry = fold(carry, buf)
    # hot-loop-end
    return np.asarray(carry)  # one sync AFTER the loop drains the pipeline

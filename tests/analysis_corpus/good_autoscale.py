"""Clean counterpart for the elastic-control-plane fixtures (ISSUE 11):
the autoscaler's handle/decision registry lives under ONE lock (server
connection threads register jobs while the policy thread sweeps), and the
decision sweep itself is a '# hot-loop' region of alert/gauge registry
reads and streak arithmetic — a gauge is a host-side Python number by
contract, never a device value the sweep would have to sync on.
Actuation (the drain + resubmit, which legitimately blocks for seconds)
runs OUTSIDE both the lock and the marked region.

Expected findings: none.  Analyzer input only — never imported.
"""

import threading


class Autoscaler:
    """Handle registry + per-job streaks: registered from connection
    threads, swept by the policy thread, so every access holds the one
    autoscaler lock."""

    def __init__(self):
        self._lock = threading.Lock()
        self._handles = {}  # guarded-by: _lock
        self._streaks = {}  # guarded-by: _lock

    def register(self, job_id, handle):
        with self._lock:
            self._handles[job_id] = handle
            self._streaks[job_id] = 0

    def unregister(self, job_id):
        with self._lock:
            self._handles.pop(job_id, None)
            self._streaks.pop(job_id, None)

    def sweep(self, alerts, page_hold, actuate):
        """One policy evaluation: decide under the lock from host-side
        registry reads, actuate outside it (a drain takes seconds and
        registration must never wait on it)."""
        decisions = []
        with self._lock:
            # hot-loop: autoscale decision sweep (alert reads + streak math)
            for job_id, handle in self._handles.items():
                paging = any(
                    a.get("state") == "PAGE"
                    for a in alerts.get(job_id, [])
                )
                streak = self._streaks[job_id] + 1 if paging else 0
                self._streaks[job_id] = streak
                if streak >= page_hold:
                    decisions.append((job_id, handle))
            # hot-loop-end
        for job_id, handle in decisions:
            actuate(job_id, handle)
        return decisions

"""The well-formed twin of bad_decodepool.py: a decode-pool shaped class
holding the serving data plane's lock discipline (ISSUE 14) — the arena
free-list and the completion queue each annotated ``# guarded-by:`` and
only ever touched under their locks, the worker loop's hot region free of
device syncs (native decode only), and the pool lock declared a leaf of
the server hierarchy.  Expected findings: none.  Analyzer input only —
never imported.
"""
# lock-order: server.StreamServer._admission < good_decodepool.GoodDecodePool._lock

import threading


def native_decode_into(buf, arena):
    """Stand-in for the ctypes call (GIL released, no device access)."""
    return len(buf)


class GoodDecodePool:
    def __init__(self):
        self._lock = threading.Condition()
        self._alock = threading.Lock()
        # recycled landing arenas
        self._free = []  # guarded-by: _alock
        # completion queue: request id -> decoded rows
        self._done = {}  # guarded-by: _lock
        self._next_id = 0  # guarded-by: _lock

    def acquire_arena(self):
        with self._alock:
            return self._free.pop() if self._free else bytearray(64)

    def release_arena(self, arena):
        with self._alock:
            self._free.append(arena)

    def submit(self, buf):
        with self._lock:
            rid = self._next_id
            self._next_id += 1
        return rid

    def reap(self, rid):
        with self._lock:
            while rid not in self._done:
                self._lock.wait(0.1)
            return self._done.pop(rid)

    def worker(self, requests):
        # hot-loop: decode worker (native calls only — no device syncs)
        for rid, buf in requests:
            arena = self.acquire_arena()
            rows = native_decode_into(buf, arena)
            with self._lock:
                self._done[rid] = (rows, arena)
                self._lock.notify_all()
        # hot-loop-end

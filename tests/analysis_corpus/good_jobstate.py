"""Lock-discipline corpus (clean): job state mutated under the manager lock.

The runtime's pattern (runtime/job.py): every lifecycle transition — and
every read that feeds one — happens inside ``with self._lock:`` where
``_lock`` IS the manager's lock, so the scheduler thread and the API
threads observe one total transition order.  Analyzer input only — never
imported.
"""

import threading


class GoodJob:
    def __init__(self, manager_lock: threading.Lock):
        self._lock = manager_lock
        self._state = "PENDING"  # guarded-by: _lock

    def to_running(self):
        with self._lock:
            if self._state == "PENDING":
                self._state = "RUNNING"

    def snapshot(self) -> str:
        with self._lock:
            return self._state

"""Clean counterpart: every cohort-registry access holds its lock, and
the collect pass defers materialization past the hot loop (lazy row
slices; the sink materializes where the solo plane would have synced).

Expected findings: none.  Analyzer input only — never imported.
"""

import threading


class CohortBoard:
    """Parked FoldRequests grouped by cohort key — written by the
    scheduler's collect pass while status/metrics threads snapshot."""

    def __init__(self):
        self._lock = threading.Lock()
        self._parked = 0  # guarded-by: _lock
        self._hwm = 0  # guarded-by: _lock

    def park(self, request):
        with self._lock:
            self._parked += 1

    def high_water(self, n):
        with self._lock:
            if n > self._hwm:
                self._hwm = n

    def snapshot(self):
        with self._lock:
            return self._parked, self._hwm


def collect(board, quanta):
    rows = []
    # hot-loop: cohort collect pass (stack rows; dispatch stays async)
    for q in quanta:
        rows.append(q.src)  # already a padded host row; no device sync
        board.park(q)
    # hot-loop-end
    return rows

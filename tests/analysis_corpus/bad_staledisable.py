"""Seeded bug: a suppression that outlived the finding it silenced.

The kernel below was rewired through the executable cache, but the
``# graft: disable=RAWJIT`` comment stayed behind — it now suppresses
nothing, and would invisibly swallow a FUTURE raw jit added on its line.

Expected findings: exactly one STALEDISABLE.
This file is analyzer input only — it is never imported.
"""

from gelly_streaming_tpu.core import compile_cache


def _make():
    def kernel(x):
        return x + 1

    return kernel


# graft: disable=RAWJIT — predates the cached_jit rewire below
step = compile_cache.cached_jit(("stale_corpus_kernel",), _make)

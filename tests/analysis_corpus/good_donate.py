"""Clean counterpart: donated carries rebound, arenas untouched until the
sanctioned drain point.

Expected findings: none.  Analyzer input only — never imported.
"""

import numpy as np

from gelly_streaming_tpu.core import compile_cache
from gelly_streaming_tpu.core.async_exec import ArenaPool, wait_ready


def _build():
    def fold(state, buf):
        return state

    return fold


fold = compile_cache.cached_jit(("corpus_fold_ok",), _build, donate_argnums=0)
pool = ArenaPool()


def run(batches):
    state = np.zeros(4)
    for buf in batches:
        state = fold(state, buf)  # donated-carry pattern: rebind immediately
    return state


def pack_and_drain(pane):
    src = pool.acquire((8,), np.int32)
    src[:4] = pane  # writes while owned (before hand-off) are fine
    dev = fold(src, pane)
    wait_ready(dev)  # the fold completed: the arena is no longer read
    pool.release(src)  # arena-live-until: drain
    return dev

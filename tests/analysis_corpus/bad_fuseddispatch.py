"""Seeded bugs: the fused-dispatch cohort registry mutated outside its
lock, and a blocking host sync inside the '# hot-loop' collect pass.

Expected findings: one HOTSYNC + three UNGUARDED (the high-water
check-then-act flags both the unlocked read and the unlocked store).
Analyzer input only — never imported.
"""

import threading

import numpy as np


class CohortBoard:
    """Parked FoldRequests grouped by cohort key — written by the
    scheduler's collect pass while status/metrics threads snapshot."""

    def __init__(self):
        self._lock = threading.Lock()
        self._parked = 0  # guarded-by: _lock
        self._hwm = 0  # guarded-by: _lock

    def park(self, request):
        self._parked += 1  # BUG: scheduler bump races the snapshot reader

    def high_water(self, n):
        if n > self._hwm:
            self._hwm = n  # BUG: check-then-act store outside the lock

    def snapshot(self):
        with self._lock:
            return self._parked, self._hwm


def collect(board, quanta):
    rows = []
    # hot-loop: cohort collect pass (stack rows; dispatch stays async)
    for q in quanta:
        rows.append(np.asarray(q.src))  # BUG: one sync restores lockstep
        board.park(q)
    # hot-loop-end
    return rows

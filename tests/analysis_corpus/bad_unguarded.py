"""Seeded bug: '# guarded-by:' state touched without its lock.

Expected findings: exactly two UNGUARDED — a module global bumped without
'with _LOCK:' and an instance attribute bumped without 'with self._lock:'.
Analyzer input only — never imported.
"""

import threading

_LOCK = threading.Lock()
_COUNT = 0  # guarded-by: _LOCK


def bump():
    global _COUNT
    _COUNT += 1  # BUG: lost-update window — two threads read the same value


class Meter:
    def __init__(self):
        self._lock = threading.Lock()
        self.total = 0  # guarded-by: _lock

    def add(self, n):
        self.total += n  # BUG: same lost-update window on the instance

"""Seeded bugs in a binned-ingest decoder dispatch (ISSUE 6 shapes): a
blocking host sync inside the '# hot-loop' decode+fold dispatch region, and
a wire-counter registry bumped without its lock.

Expected findings: exactly one HOTSYNC and one UNGUARDED.
Analyzer input only — never imported.
"""

import threading

import numpy as np

_WIRE_LOCK = threading.Lock()
_WIRE_BYTES = 0  # guarded-by: _WIRE_LOCK


def record_shipped(nbytes):
    global _WIRE_BYTES
    _WIRE_BYTES += nbytes  # BUG: pack-thread bump without the lock


def dispatch_compressed(bufs, fold, carry):
    # hot-loop: compressed wire dispatch (decode fuses into the fold)
    for buf in bufs:
        carry = fold(carry, buf)
        np.asarray(carry)  # BUG: per-batch download restores lockstep
    # hot-loop-end
    return carry

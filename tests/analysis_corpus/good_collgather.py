"""Clean counterpart: delta-buffer reconciliation, gathers sanctioned.

Expected findings: none.  Analyzer input only — never imported.
"""

import jax
import jax.numpy as jnp
from jax import lax

from gelly_streaming_tpu.parallel import routing


def stream_step(block, changed, values, num_shards, axis, cap):
    # cross-shard reconciliation ships only the changed rows, pow2-bucketed
    recv_rows, recv_vals, sent, occ, spilled = routing.exchange_slab_deltas(
        changed, values, num_shards, cap, axis
    )
    return routing.apply_block_deltas(block, recv_rows, recv_vals, "min", 0)


def emit_summary(block, num_shards, axis):
    full = routing.gather_blocks(block, num_shards, axis)  # gather-ok: emit boundary — replicated view for the emitted record
    return jnp.min(full)


def snapshot_seen(seen, axis):
    gathered = lax.all_gather(seen, axis)  # gather-ok: snapshot boundary download
    extra = jax.lax.all_gather(  # gather-ok: emit — marker honored on the attribute's line
        seen, axis
    )
    return gathered, extra

"""Seeded bugs for the health-plane fixtures (ISSUE 10): the event
journal's '# guarded-by:' ring/cursor/file written without the lock (two
racing emitters interleave seq bumps — lost or overwritten journal lines,
exactly the record a post-mortem replay would need), and a device sync
smuggled into the SLO monitor's evaluation sweep (materializing a gauge
from a device array blocks the monitor tick on the data plane it is
supposed to merely observe).

Expected findings: one HOTSYNC, three UNGUARDED.  Analyzer input only —
never imported.
"""

import json
import threading

import numpy as np

_CAP = 1024


class EventJournal:
    def __init__(self):
        self._lock = threading.Lock()
        self._ring = [None] * _CAP  # guarded-by: _lock
        self._seq = 0  # guarded-by: _lock
        self._file = None  # guarded-by: _lock

    def emit(self, kind, fields):
        record = {"kind": kind, **fields}
        self._ring[0] = record  # BUG: racing emitters overwrite the slot
        self._seq += 1  # BUG: lost-update window on the cursor
        self._file.write(json.dumps(record) + "\n")  # BUG: races close()
        return record


def monitor_sweep(specs, gauges, clock, evaluate):
    transitions = []
    # hot-loop: SLO evaluation sweep (gauge reads + burn math, no syncs)
    for spec in specs:
        # BUG: a device-array gauge materialized inline stalls the
        # monitor tick on the device pipeline it is observing
        value = float(np.asarray(gauges[spec.key]))
        t0 = clock()
        transitions.append(evaluate(spec, value, t0))
    # hot-loop-end
    return transitions

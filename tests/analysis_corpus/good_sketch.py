"""Clean counterpart: every sketch-registry access holds the registry lock
(the utils/metrics.py discipline the sketch contract table ships with).

Expected findings: none.  Analyzer input only — never imported.
"""

import threading

_SKETCH_LOCK = threading.Lock()
_SKETCH = {"sketch_state_bytes": 0}  # guarded-by: _SKETCH_LOCK
_SKETCH_JOBS = {}  # guarded-by: _SKETCH_LOCK


def sketch_register(job, kind, state_bytes):
    with _SKETCH_LOCK:
        _SKETCH["sketch_state_bytes"] += state_bytes
        _SKETCH_JOBS[job] = {"kind": kind, "state_bytes": state_bytes}


def sketch_stats():
    with _SKETCH_LOCK:
        return dict(_SKETCH)


def all_sketch_stats():
    with _SKETCH_LOCK:
        return {j: dict(row) for j, row in _SKETCH_JOBS.items()}


def reset_sketch_stats():
    with _SKETCH_LOCK:
        _SKETCH["sketch_state_bytes"] = 0
        _SKETCH_JOBS.clear()

"""The well-formed twin of bad_toctou.py: check and act share ONE
acquisition (the atomic admission step), the double-checked-locking shape
re-checks under the write's own acquisition, and a ``# holds-lock:``
helper is one critical section by contract.  Expected findings: none.
Analyzer input only — never imported.
"""

import threading


class GoodCaps:
    def __init__(self):
        self._lock = threading.Lock()
        self._jobs = {}  # guarded-by: _lock

    def admit(self, key, job, cap):
        # check -> act is one atomic step under one acquisition
        with self._lock:
            if len(self._jobs) < cap:
                self._jobs[key] = job
                return True
        return False

    def put_once_fastpath(self, key, val):
        with self._lock:
            present = key in self._jobs
        if not present:
            with self._lock:
                # the double-checked shape: the RE-CHECK under the write's
                # own acquisition makes the outer stale read harmless
                if key not in self._jobs:
                    self._jobs[key] = val

    # holds-lock: _lock
    def _admit_locked(self, key, job, cap):
        # the whole function is one critical section by contract
        if len(self._jobs) < cap:
            self._jobs[key] = job
            return True
        return False

    def admit_via_helper(self, key, job, cap):
        with self._lock:
            return self._admit_locked(key, job, cap)

"""Seeded bug: raw jax.jit call sites bypassing the executable cache.

Expected findings: exactly four RAWJIT — the decorator form, the call
form, the ``import jax as _jax`` alias that used to slip past the name
match, and the ``partial(jax.jit, ...)`` decorator-with-kwargs operand.
This file is analyzer input only — it is never imported.
"""

from functools import partial

import jax
import jax as _jax


@jax.jit
def kernel(x):
    return x + 1


def make_stream_step(state_fn):
    return jax.jit(state_fn, donate_argnums=0)


@partial(jax.jit, static_argnums=(1,))
def bucketed_kernel(x, width):
    return x[:width]


aliased_step = _jax.jit(lambda x: x * 2)

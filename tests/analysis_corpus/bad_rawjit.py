"""Seeded bug: raw jax.jit call sites bypassing the executable cache.

Expected findings: exactly two RAWJIT (decorator + call form).
This file is analyzer input only — it is never imported.
"""

import jax


@jax.jit
def kernel(x):
    return x + 1


def make_stream_step(state_fn):
    return jax.jit(state_fn, donate_argnums=0)

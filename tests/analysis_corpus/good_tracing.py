"""Clean counterpart for the tracing fixtures (ISSUE 9): the flight
recorder's ring is '# guarded-by:' its lock and every access holds it,
and the dispatch hot loop's off-path tracing cost is a branch — no host
syncs sneak in with the span marks.

Expected findings: none.  Analyzer input only — never imported.
"""

import threading
import time

_CAP = 256


class FlightRecorder:
    """Fixed-capacity span ring: drain threads of many jobs record while
    server threads read, so the ring state lives under one lock."""

    def __init__(self):
        self._lock = threading.Lock()
        self._ring = [None] * _CAP  # guarded-by: _lock
        self._next = 0  # guarded-by: _lock

    def record(self, span):
        with self._lock:
            self._ring[self._next % _CAP] = span
            self._next += 1

    def last(self, n):
        with self._lock:
            end = self._next
            return [
                self._ring[i % _CAP] for i in range(max(0, end - n), end)
            ]


def dispatch_loop(items, dispatch, recorder, sampler):
    """The instrumented dispatch loop: sampling off = one branch per
    window; sampled windows mark stages with clock reads only."""
    pending = []
    # hot-loop: traced window dispatch (no per-window host syncs)
    for meta, dev in items:
        span = sampler.begin(meta) if sampler is not None else None
        t0 = time.perf_counter()
        handle = dispatch(meta, dev)
        if span is not None:
            span.mark("dispatch", t0)
        pending.append((span, handle))
    # hot-loop-end
    for span, handle in pending:
        if span is not None:
            recorder.record(span)
    return pending

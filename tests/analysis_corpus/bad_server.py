"""Seeded bug: the serving plane's connection registry touched OUTSIDE the
server lock.

The fixture for the lock-discipline pass over runtime/server.py's
discipline: the connection set is ``# guarded-by: _lock`` because the
accept loop adds while connection handlers discard and shutdown iterates —
an unlocked len()-check-then-add races two accepts past the connection
cap, and an unlocked discard during shutdown's iteration throws.

Expected findings: exactly two UNGUARDED — the unlocked read in the cap
check and the unlocked add.  Analyzer input only — never imported.
"""

import threading


class BadServer:
    def __init__(self, max_connections: int):
        self._max = max_connections
        self._lock = threading.Lock()
        self._conns = set()  # guarded-by: _lock

    def try_accept(self, sock) -> bool:
        # BUG: check-then-add without the server lock — two concurrent
        # accepts both pass the cap check and both register
        if len(self._conns) >= self._max:
            return False
        self._conns.add(sock)
        return True

    def teardown(self, sock) -> None:
        with self._lock:
            self._conns.discard(sock)

"""Seeded bug: touching buffers after donating them.

Expected findings: exactly two DONATE — a read of a donated carry before
rebinding, and a write into an arena after it was handed to the device.
Analyzer input only — never imported.
"""

import numpy as np

from gelly_streaming_tpu.core import compile_cache
from gelly_streaming_tpu.core.async_exec import ArenaPool


def _build():
    def fold(state, buf):
        return state

    return fold


fold = compile_cache.cached_jit(("corpus_fold",), _build, donate_argnums=0)
pool = ArenaPool()


def run(batches):
    state = np.zeros(4)
    for buf in batches:
        out = fold(state, buf)
        total = state.sum()  # BUG: state's buffer was donated to fold
        state = out
    return state, total


def pack(pane):
    src = pool.acquire((8,), np.int32)
    dev = fold(src, pane)
    src[0] = 1  # BUG: the in-flight fold may alias this memory zero-copy
    return dev

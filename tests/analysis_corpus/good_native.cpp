// Clean twin for the nativecheck pass family (#10-#13): the same shapes
// as bad_native.cpp written to contract, plus the C++ suppression grammar
// (// graft: disable=CODE — justification) — the whole file must scan to
// zero findings in tests/test_analysis.py.
#include <cstdint>
#include <cstdio>
#include <cstdlib>
#include <cstring>

extern "C" {

// matches utils/native.py NATIVE_SIGNATURES exactly: (char*) -> int64
int64_t count_rows(const char* path) {
  FILE* f = fopen(path, "rb");
  if (!f) return -1;
  char* buf = static_cast<char*>(malloc((size_t)1 << 12));
  if (!buf) {  // alloc-failure guard: nothing to leak, nothing to deref
    fclose(f);
    return -1;
  }
  int64_t rows = 0;
  size_t nread;
  while ((nread = fread(buf, 1, (size_t)1 << 12, f)) > 0) {
    for (size_t i = 0; i < nread; ++i) rows += (buf[i] == '\n');
  }
  free(buf);  // every return path below the allocation releases it
  fclose(f);
  return rows;
}

// fixed untrusted window done right: the caller contract is exactly 12
// prefix bytes, and every read is a constant index inside it
// untrusted: prefix[12]
int32_t gly1_probe_prefix(const uint8_t* prefix, int64_t max_header,
                          int64_t max_payload, int64_t* header_len,
                          int64_t* payload_len) {
  uint32_t h = ((uint32_t)prefix[4] << 24) | ((uint32_t)prefix[5] << 16) |
               ((uint32_t)prefix[6] << 8) | (uint32_t)prefix[7];
  uint32_t p = ((uint32_t)prefix[8] << 24) | ((uint32_t)prefix[9] << 16) |
               ((uint32_t)prefix[10] << 8) | (uint32_t)prefix[11];
  *header_len = (int64_t)h;
  *payload_len = (int64_t)p;
  if (prefix[0] != 'G' || prefix[1] != 'L' || prefix[2] != 'Y' ||
      prefix[3] != '1') {
    return -1;
  }
  if ((int64_t)h > max_header) return -2;
  if ((int64_t)p > max_payload) return -3;
  return 0;
}

// length-parameter untrusted window done right: nbytes is compared before
// any byte of buf is touched, the size is widened before the arithmetic,
// and the scratch pointer is released on every path past its allocation
// untrusted: buf[nbytes]
int64_t decode_wire_into(const uint8_t* buf, int64_t nbytes, int64_t n,
                         int32_t width_code, int32_t capacity, int32_t sort,
                         int32_t* out_src, int32_t* out_dst) {
  if (width_code != 2 || sort != 0) return -4;
  if (n < 0 || capacity <= 0) return -1;
  if (nbytes != 4 * n) return -1;  // the dominating bounds comparison
  int32_t* tmp = static_cast<int32_t*>(malloc(((size_t)n + 1) * 4));
  if (!tmp) return -4;
  for (int64_t i = 0; i < n; ++i) {
    uint32_t v = (uint32_t)buf[2 * i] | ((uint32_t)buf[2 * i + 1] << 8);
    if ((int32_t)v >= capacity) {
      free(tmp);  // refusal path releases before returning
      return -2;
    }
    tmp[i] = (int32_t)v;
  }
  memcpy(out_src, tmp, (size_t)n * 4);
  memcpy(out_dst, tmp, (size_t)n * 4);
  free(tmp);
  return n;
}

}  // extern "C"

namespace {

// a static helper is no ctypes export (no NATIVEABI row needed), and a
// justified suppression silences the one rule the caller's clamp makes
// moot — the framework must honor the C++ grammar here
int64_t scratch_probe(int64_t n) {
  // graft: disable=NATIVEOVFL — n is clamped to <= 4096 by the only caller
  char* p = static_cast<char*>(malloc(n * 2));
  if (!p) return -1;
  p[0] = 0;
  free(p);
  return n;
}

}  // namespace

extern "C" int64_t count_rows_range(const char* path, int64_t begin,
                                    int64_t end_off) {
  (void)path;
  return scratch_probe(end_off - begin);
}

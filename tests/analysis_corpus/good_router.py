"""The well-formed twin of bad_router.py: every touch of the pin table
and relay set holds its declared lock, and both the failover and the
placement paths acquire in the one declared order
(``# lock-order: _REGISTRY < _PLACEMENT``) — failover nests placement
under registry, and placement resolves liveness BEFORE taking its own
lock instead of nesting the registry lock inside it.
Expected findings: none.  Analyzer input only — never imported.
"""

import threading

# lock-order: _REGISTRY < _PLACEMENT

_REGISTRY = threading.Lock()
_PLACEMENT = threading.Lock()

_ALIVE = {}  # guarded-by: _REGISTRY
_PINS = {}  # guarded-by: _PLACEMENT


def pin(key, backend):
    with _PLACEMENT:
        _PINS[key] = backend


def failover(name, standby):
    with _REGISTRY:
        _ALIVE[name] = False
        _redirect(name, standby)


def _redirect(name, standby):
    # nested under _REGISTRY in failover(): agrees with the declared order
    with _PLACEMENT:
        _PINS[name] = standby


def place(key):
    # liveness first (registry lock released), THEN the placement lock:
    # the same _REGISTRY-before-_PLACEMENT order failover takes
    alive = _probe_alive()
    with _PLACEMENT:
        backend = _PINS.get(key)
        return backend if backend in alive else None


def _probe_alive():
    with _REGISTRY:
        return {name for name, up in _ALIVE.items() if up}


class Router:
    def __init__(self):
        self._lock = threading.Lock()
        self._relays = set()  # guarded-by: _lock

    def attach(self, relay):
        with self._lock:
            self._relays.add(relay)

"""Seeded bugs for the elastic-control-plane fixtures (ISSUE 11): the
autoscaler's '# guarded-by:' handle/streak registry written without the
lock (a connection thread registering while the policy thread sweeps
loses the registration — or the sweep iterates a dict being resized under
it), and a device sync smuggled into the decision sweep (materializing a
"gauge" from a device array blocks the policy tick on the data plane it
is supposed to merely observe — and, transitively, delays every pending
rescale behind one fold).

Expected findings: one HOTSYNC, five UNGUARDED (register's two
lost-update writes, the sweep's unguarded dict iteration, and the streak
read-modify-write pair).  Analyzer input only — never imported.
"""

import threading

import numpy as np


class Autoscaler:
    def __init__(self):
        self._lock = threading.Lock()
        self._handles = {}  # guarded-by: _lock
        self._streaks = {}  # guarded-by: _lock

    def register(self, job_id, handle):
        self._handles[job_id] = handle  # BUG: races the sweeping policy thread
        self._streaks[job_id] = 0  # BUG: lost registration under contention

    def sweep(self, gauges, page_hold, actuate):
        decisions = []
        # hot-loop: autoscale decision sweep (alert reads + streak math)
        for job_id, handle in self._handles.items():
            # BUG: a device-array gauge materialized inline stalls the
            # policy tick on the device pipeline it is observing
            lag = float(np.asarray(gauges[job_id]))
            streak = self._streaks.get(job_id, 0) + 1 if lag > 0 else 0
            self._streaks[job_id] = streak  # BUG: unguarded streak write
            if streak >= page_hold:
                decisions.append((job_id, handle))
        # hot-loop-end
        for job_id, handle in decisions:
            actuate(job_id, handle)
        return decisions

"""Clean counterpart: the same kernels routed through the executable cache.

Expected findings: none.  Analyzer input only — never imported.
"""

from gelly_streaming_tpu.core import compile_cache


def make():
    def kernel(x):
        return x + 1

    return kernel


step = compile_cache.cached_jit(("corpus_kernel",), make)


def make_stream_step(state_fn):
    return compile_cache.cached_jit(
        ("corpus_stream_step", state_fn), lambda: state_fn, donate_argnums=0
    )

"""Seeded bug: job lifecycle state mutated OUTSIDE the manager lock.

The runtime fixture for the lock-discipline pass (runtime/job.py's
discipline): a job's ``_state`` is '# guarded-by: _lock' (the MANAGER's
lock, shared by reference), so a transition taken without it races the
scheduler's state checks — a cancelled job can be re-marked RUNNING after
the scheduler already closed its iterator.

Expected findings: exactly two UNGUARDED — the unlocked read in the guard
test and the unlocked write of the transition.  Analyzer input only —
never imported.
"""

import threading


class BadJob:
    def __init__(self, manager_lock: threading.Lock):
        self._lock = manager_lock
        self._state = "PENDING"  # guarded-by: _lock

    def to_running(self):
        # BUG: check-then-act without the manager lock — a concurrent
        # cancel() between the read and the write is silently overwritten
        if self._state == "PENDING":
            self._state = "RUNNING"

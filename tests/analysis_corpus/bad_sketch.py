"""Seeded bug: the sketch-summary contract registry mutated lock-free.

Models utils/metrics.py's sketch registry (ISSUE 19): byte totals and the
per-job contract table are '# guarded-by:' the registry lock because
registrations land from the server's submit thread while scrapes drain
from metrics/bench threads.  Expected findings: exactly two UNGUARDED —
the totals dict bumped and the contract row installed without
'with _SKETCH_LOCK:'.  Analyzer input only — never imported.
"""

import threading

_SKETCH_LOCK = threading.Lock()
_SKETCH = {"sketch_state_bytes": 0}  # guarded-by: _SKETCH_LOCK
_SKETCH_JOBS = {}  # guarded-by: _SKETCH_LOCK


def sketch_register(job, kind, state_bytes):
    # BUG: lost-update window — a concurrent register reads the same total
    _SKETCH["sketch_state_bytes"] += state_bytes
    # BUG: a concurrent snapshot iterates the dict mid-insert
    _SKETCH_JOBS[job] = {"kind": kind, "state_bytes": state_bytes}


def sketch_stats():
    with _SKETCH_LOCK:
        return dict(_SKETCH)

"""Clean counterpart for the health-plane fixtures (ISSUE 10): the event
journal's ring / cursor / file mirror all live under ONE lock (scheduler,
connection, and monitor threads emit while the events verb tails), and
the SLO monitor's evaluation sweep is a '# hot-loop' region of counter
reads and dict math — a gauge is a host-side Python number by contract,
never a device value the sweep would have to sync on.

Expected findings: none.  Analyzer input only — never imported.
"""

import json
import threading

_CAP = 1024


class EventJournal:
    """Bounded ring + JSONL mirror: emitters on many threads, tailers on
    server threads, so every access holds the journal lock."""

    def __init__(self):
        self._lock = threading.Lock()
        self._ring = [None] * _CAP  # guarded-by: _lock
        self._seq = 0  # guarded-by: _lock
        self._file = None  # guarded-by: _lock

    def emit(self, kind, fields):
        with self._lock:
            record = {"seq": self._seq, "kind": kind, **fields}
            self._ring[self._seq % _CAP] = record
            self._seq += 1
            if self._file is not None:
                self._file.write(json.dumps(record) + "\n")
        return record

    def tail(self, n):
        with self._lock:
            end = self._seq
            return [
                self._ring[i % _CAP] for i in range(max(0, end - n), end)
            ]


def monitor_sweep(specs, gauges, clock, evaluate):
    """The SLO monitor's evaluation loop: per tick it reads each spec's
    gauge, stamps the tick, and feeds the burn-rate state machine —
    host-side arithmetic only, so the sweep can run at tick rate without
    ever stalling a data-plane thread."""
    transitions = []
    # hot-loop: SLO evaluation sweep (gauge reads + burn math, no syncs)
    for spec in specs:
        value = gauges.get(spec.key)
        t0 = clock()
        if value is not None:
            transitions.append(evaluate(spec, value, t0))
    # hot-loop-end
    return transitions

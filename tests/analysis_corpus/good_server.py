"""Lock-discipline corpus (clean): the serving plane's connection registry
mutated only under the server lock.

The server's pattern (runtime/server.py): the accept loop, every
connection handler's teardown, and the shutdown path all touch the
connection set and the served-job registry concurrently, so both are
``# guarded-by: _lock`` and every access takes ``with self._lock:`` —
the count-check-then-add on accept is one atomic step, so the connection
cap cannot be raced past.  Analyzer input only — never imported.
"""

import threading


class GoodServer:
    def __init__(self, max_connections: int):
        self._max = max_connections
        self._lock = threading.Lock()
        self._conns = set()  # guarded-by: _lock
        self._jobs = {}  # guarded-by: _lock

    def try_accept(self, sock) -> bool:
        with self._lock:
            if len(self._conns) >= self._max:
                return False
            self._conns.add(sock)
            return True

    def teardown(self, sock) -> None:
        with self._lock:
            self._conns.discard(sock)

    def lookup(self, key):
        with self._lock:
            return self._jobs.get(key)

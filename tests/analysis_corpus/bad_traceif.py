"""Seeded bug: Python control flow / coercions on traced values inside
compile-cache-dispatched kernels.

Expected findings: exactly one TRACEIF (the value branch) and two
TRACECAST (the int() coercion and the .item() read).
Analyzer input only — never imported.
"""

from gelly_streaming_tpu.core import compile_cache


def make():
    def kernel(x, n, flag):
        if x > 0:  # BUG: value branch concretizes the tracer
            return x
        return x + int(n)  # BUG: int() is a host sync on a tracer

    return kernel


def make_reader():
    def reader(y):
        return y.item()  # BUG: .item() concretizes the tracer

    return reader


step = compile_cache.cached_jit(("corpus_trace",), make, static_argnums=(2,))
read = compile_cache.cached_jit(("corpus_read",), make_reader)

"""The well-formed twin of bad_holdslock.py: every ``# holds-lock:``
contract is honored at every call site, a declared helper touches only
state its declaration covers, and a two-lock helper declares both.
Expected findings: none.  Analyzer input only — never imported.
"""

import threading


class GoodRegistry:
    def __init__(self):
        self._lock = threading.Lock()
        self._mu = threading.Lock()
        self._jobs = {}  # guarded-by: _lock
        self._stats = {}  # guarded-by: _mu

    # holds-lock: _lock
    def _evict(self, key):
        self._jobs.pop(key, None)

    # holds-lock: _lock, _mu
    def _account(self, key):
        # both registries move together; the contract declares both locks
        self._stats[key] = len(self._jobs)

    def shutdown(self, key):
        with self._lock:
            self._evict(key)

    def rebalance(self, key):
        with self._lock:
            with self._mu:
                self._account(key)

    # holds-lock: _lock
    def _chain(self, key):
        # a holds-lock function may call another with the same contract:
        # the declared entry set satisfies the callee
        self._evict(key)

    def flush(self, key):
        with self._lock:
            self._chain(key)

"""Seeded bug: the split-lock check-then-act — the exact shape of the
PR 7 tenant-cap steal.  Both the check and the act correctly take the
registry's lock (so pass #3 sees nothing), but in TWO separate
acquisitions: two concurrent ``admit`` calls can both read ``len == cap-1``
before either registers, and both insert — the cap is pierced.

Expected findings: exactly two TOCTOU — the tainted-count steal in
``admit`` and the direct membership-check steal in ``put_once``.
Analyzer input only — never imported.
"""

import threading


class BadCaps:
    def __init__(self):
        self._lock = threading.Lock()
        self._jobs = {}  # guarded-by: _lock

    def admit(self, key, job, cap):
        with self._lock:
            live = len(self._jobs)
        if live < cap:
            # BUG: the cap check used a COUNT from a previous acquisition;
            # a concurrent admit interleaves between the regions
            with self._lock:
                self._jobs[key] = job
                return True
        return False

    def put_once(self, key, val):
        with self._lock:
            present = key in self._jobs
        if not present:
            with self._lock:
                # BUG: same split — two put_once calls both see absent
                self._jobs[key] = val

"""Seeded bugs: the decode-pool discipline violations graftcheck must
catch (ISSUE 14).

* the worker publishes into the ``# guarded-by: _lock`` completion queue
  WITHOUT the lock — the lost-update a reaping connection thread cannot
  reproduce in an interleaving test (one UNGUARDED), and the arena
  free-list's unlocked check-then-pop adds two more (both accesses);
* the worker's hot region materializes a device array per request
  (``np.asarray``) — a per-buffer device sync inside the decode loop,
  exactly the lockstep the GIL-free pool exists to remove (one HOTSYNC).

Expected findings: exactly
["HOTSYNC", "UNGUARDED", "UNGUARDED", "UNGUARDED"].
Analyzer input only — never imported.
"""

import threading

import numpy as np


def native_decode_into(buf, arena):
    return len(buf)


class BadDecodePool:
    def __init__(self):
        self._lock = threading.Condition()
        self._alock = threading.Lock()
        self._free = []  # guarded-by: _alock
        self._done = {}  # guarded-by: _lock

    def reap(self, rid):
        with self._lock:
            while rid not in self._done:
                self._lock.wait(0.1)
            return self._done.pop(rid)

    def worker(self, requests, device_probe):
        # hot-loop: decode worker
        for rid, buf in requests:
            arena = (
                self._free.pop()  # BUG: free-list touched without _alock
                if self._free
                else bytearray(64)
            )
            rows = native_decode_into(buf, arena)
            np.asarray(device_probe)  # BUG: device sync per decoded buffer
            # BUG: completion queue published without _lock — a racing
            # reap() can read a half-updated map and lose this result
            self._done[rid] = (rows, arena)
        # hot-loop-end

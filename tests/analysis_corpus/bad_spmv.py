"""Seeded bug: the direction-optimized SpMV kernel done wrong — a Python
branch on the traced frontier density picks the lowering (concretizes the
tracer; at best a ConcretizationTypeError, at worst a per-density retrace),
and the window dispatch loop syncs every result back to the host.

Expected findings: exactly one TRACEIF and one HOTSYNC.
Analyzer input only — never imported.
"""

import jax.numpy as jnp
import numpy as np

from gelly_streaming_tpu.core import compile_cache

CAPACITY = 1024


def make():
    def step(d_src, d_w, d_msk, x, fm, thr):
        if jnp.sum(fm) / CAPACITY > thr:  # BUG: value branch on the density
            cand = jnp.where(d_msk, x[d_src] + d_w, jnp.float32(1e30))
            return jnp.minimum(x, cand[:CAPACITY])
        return x

    return step


step = compile_cache.cached_jit(("corpus_spmv_step",), make)


def drive(panes, x, fm, thr):
    dists = []
    # hot-loop: per-window direction-optimized dispatch
    for pane in panes:
        x = step(pane.d_src, pane.d_w, pane.d_msk, x, fm, thr)
        dists.append(np.asarray(x))  # BUG: one sync per window = lockstep
    # hot-loop-end
    return dists

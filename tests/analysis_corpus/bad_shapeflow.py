"""Seeded bugs: data-dependent shapes crossing compile boundaries.

Expected findings (shapeflow): UNBUCKETED at the data-dependent
compile-cache key, UNBUCKETED at the interprocedural call site whose
argument feeds a callee's key, KEYLEAK for the closed-over scale the key
omits, and DTYPEDRIFT for the bare Python scalar crossing the cached
kernel boundary.

Unlike most corpus files this one IS imported: tests/test_shapeflow.py
loads it and drives ``unbucketed_step`` to prove the seeded UNBUCKETED
really recompiles (compile_cache stats), so module import must stay
side-effect-free — functions only, nothing called at module scope.
"""

import numpy as np

from gelly_streaming_tpu.core import compile_cache


def _build_fold():
    import jax.numpy as jnp

    def fold(x):
        return jnp.sum(x)

    return fold


def unbucketed_step(values):
    # the live count is data-dependent: every distinct batch mints a
    # fresh executable
    live = [v for v in values if v > 0.0]
    n = len(live)
    fn = compile_cache.cached_jit(("bad_fold", n), _build_fold)
    import jax.numpy as jnp

    return fn(jnp.zeros((max(n, 1),), jnp.float32))


def _fold_for(n):
    return compile_cache.cached_jit(("bad_interp_fold", n), _build_fold)


def interp_step(v):
    # the dynamic unique-count flows INTO _fold_for's key: only the
    # interprocedural obligation flow can see it from this line
    return _fold_for(len(np.unique(v)))


def make_scaled_fold(scale):
    def build():
        import jax.numpy as jnp

        def fold(x):
            return jnp.sum(x) * scale

        return fold

    # the key omits `scale`, so two folds with different scales collide
    # on one cache entry and silently share the first one's executable
    return compile_cache.cached_jit(("bad_scaled_fold",), build)


def _build_scaled():
    import jax.numpy as jnp

    def fold(x, s):
        return jnp.sum(x) * s

    return fold


_drift_fold = compile_cache.cached_jit(("bad_drift_fold",), _build_scaled)


def drift_step(x):
    # bare Python float crosses the cached boundary: weak-type promotion
    # forks cache entries by call-site literal
    return _drift_fold(x, 0.5)

"""The well-formed twin of bad_lockorder.py: every path acquires in the
one declared order (``# lock-order: _ADMIT < _STATE``), the helper's
nested acquisition agrees with it interprocedurally, and the RLock's
re-entrant self-acquisition (the server ``_admission`` shape) is exempt.
Expected findings: none.  Analyzer input only — never imported.
"""

import threading

# lock-order: _ADMIT < _STATE

_ADMIT = threading.Lock()
_STATE = threading.Lock()
_REENTRANT = threading.RLock()


def drain():
    with _ADMIT:
        _flush()


def _flush():
    with _STATE:
        pass


def rebalance():
    # same order as drain: _ADMIT first, then the nested _STATE
    with _ADMIT:
        with _STATE:
            pass


def admit():
    with _REENTRANT:
        _account()


def _account():
    # re-entrant re-acquisition while already held: exempt (RLock)
    with _REENTRANT:
        pass

"""Clean counterpart: static-parameter branches, structural tests, and
on-device selects only.

Expected findings: none.  Analyzer input only — never imported.
"""

import jax.numpy as jnp

from gelly_streaming_tpu.core import compile_cache


def make():
    def kernel(x, n, flag):
        if flag:  # static_argnums parameter: concrete by contract
            return jnp.where(x > 0, x, n)  # value select stays on device
        if x is None:  # structural: decided at trace time
            return n
        if x.shape[0] > 2:  # shapes are trace-time constants
            return x + n
        return x - n

    return kernel


step = compile_cache.cached_jit(("corpus_trace_ok",), make, static_argnums=(2,))

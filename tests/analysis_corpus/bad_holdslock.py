"""Seeded bugs: the interprocedural lock contracts pass #3 cannot see.

The helper-mutates-under-caller's-lock shape (runtime/manager.py's
``_release`` / ``_evict_old_terminal`` discipline): ``_evict`` declares
``# holds-lock: _lock`` and mutates the guarded registry relying on its
caller's acquisition — invisible to the intraprocedural pass #3, which
delegates annotated functions to pass #6.

Expected findings: exactly one NOHOLD (the unlocked call to ``_evict`` in
``tick``) and one HELDLOCK (``report`` declares ``_lock`` but touches
state guarded by ``_mu`` without taking it).  Analyzer input only — never
imported.
"""

import threading


class BadRegistry:
    def __init__(self):
        self._lock = threading.Lock()
        self._mu = threading.Lock()
        self._jobs = {}  # guarded-by: _lock
        self._stats = {}  # guarded-by: _mu

    # holds-lock: _lock
    def _evict(self, key):
        # fine BY CONTRACT: the caller holds _lock (pass #6 checks the
        # call sites; pass #3 delegates this function)
        self._jobs.pop(key, None)

    def shutdown(self, key):
        with self._lock:
            self._evict(key)  # ok: lock held across the call

    def tick(self, key):
        # BUG: the helper's contract says _lock must be held here — a
        # concurrent shutdown() can evict between our check and the
        # helper's mutation
        self._evict(key)

    # holds-lock: _lock
    def report(self):
        # BUG: _stats is guarded by _mu, which this function neither
        # declares nor takes — the caller's _lock does not protect it
        return len(self._stats)

"""Seeded bug: full-state gathers inside a streaming-step kernel.

Expected findings: exactly three COLLGATHER (raw lax.all_gather, a
jax.lax.all_gather of the whole partial summary, and an unsanctioned
gather_blocks call).  Analyzer input only — never imported.
"""

import jax
import jax.numpy as jnp
from jax import lax

from gelly_streaming_tpu.parallel import routing


def stream_step(carry, src, dst, mask, axis):
    states, summary = carry
    summary = summary.at[src].min(jnp.where(mask, dst, summary.shape[0]))
    # per-dispatch reconciliation by gathering EVERY shard's full partial:
    # the O(C*S) wall the owner-sharded plane removed
    gathered = lax.all_gather(summary, axis)
    merged = jnp.min(gathered, axis=0)
    also = jax.lax.all_gather(states, axis)
    return (also, merged)


def peek_blocks(block, num_shards, axis):
    # reassembling the replicated view mid-stream, not at an emit boundary
    return routing.gather_blocks(block, num_shards, axis)

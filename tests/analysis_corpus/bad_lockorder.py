"""Seeded bug: a lock-order inversion SPANNING TWO FUNCTIONS — the
interprocedural case pass #3 (and any per-function scan) provably misses:
no single function acquires both locks, yet ``drain`` (A then, via
``_flush``, B) racing ``rebalance`` (B then, via ``_recount``, A)
deadlocks with each thread holding the other's next lock.

Expected findings: exactly one LOCKORDER naming the A->B->A cycle with
both acquisition chains.  Analyzer input only — never imported.
"""

import threading

_ADMIT = threading.Lock()
_STATE = threading.Lock()


def drain():
    with _ADMIT:
        _flush()


def _flush():
    with _STATE:
        pass


def rebalance():
    with _STATE:
        _recount()


def _recount():
    with _ADMIT:
        pass

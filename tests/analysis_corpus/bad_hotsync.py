"""Seeded bug: a blocking host sync inside a '# hot-loop' region.

Expected findings: exactly one HOTSYNC.
Analyzer input only — never imported.
"""

import numpy as np


def drain(xs):
    out = []
    # hot-loop: dispatch loop
    for x in xs:
        out.append(np.asarray(x))  # BUG: one sync restores lockstep
    # hot-loop-end
    return out

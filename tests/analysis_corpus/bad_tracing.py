"""Seeded bugs for the tracing fixtures (ISSUE 9): the flight recorder's
'# guarded-by:' ring written without its lock (two racing drain threads
interleave _next bumps and overwrite each other's slot — lost spans), and
a blocking host sync smuggled into the traced dispatch hot loop (reading
the span's fold result materializes the window inline, turning the
overlapped pipeline back into per-window lockstep).

Expected findings: one HOTSYNC, two UNGUARDED.  Analyzer input only —
never imported.
"""

import threading
import time

import numpy as np

_CAP = 256


class FlightRecorder:
    def __init__(self):
        self._lock = threading.Lock()
        self._ring = [None] * _CAP  # guarded-by: _lock
        self._next = 0  # guarded-by: _lock

    def record(self, span):
        self._ring[self._next % _CAP] = span  # BUG: racing drains lose spans
        self._next += 1  # BUG: lost-update window on the cursor


def dispatch_loop(items, dispatch, recorder, sampler):
    pending = []
    # hot-loop: traced window dispatch (no per-window host syncs)
    for meta, dev in items:
        span = sampler.begin(meta) if sampler is not None else None
        t0 = time.perf_counter()
        handle = dispatch(meta, dev)
        if span is not None:
            # BUG: materializing the result to annotate the span blocks
            # the dispatch loop on the device every sampled window
            span.annotate(total=float(np.asarray(handle).sum()))
            span.mark("dispatch", t0)
        pending.append((span, handle))
    # hot-loop-end
    return pending

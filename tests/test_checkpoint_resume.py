"""Aggregation checkpoint/resume tests: a restored run continues exactly where
the snapshot left off (Merger ListCheckpointed semantics generalized,
SummaryAggregation.java:127-135)."""

import os

from gelly_streaming_tpu.core.config import StreamConfig
from gelly_streaming_tpu.core.stream import EdgeStream
from gelly_streaming_tpu.library.connected_components import ConnectedComponents

CFG = StreamConfig(vertex_capacity=16, max_degree=16)

EDGES_T = [
    (1, 2, 0, 10),
    (3, 4, 0, 110),
    (2, 3, 0, 210),
    (5, 6, 0, 310),
]


def _timed_stream(edges):
    return EdgeStream.from_collection(edges, CFG, batch_size=1, with_time=True)


def test_checkpoint_resume_matches_uninterrupted_run(tmp_path):
    ckpt = os.path.join(str(tmp_path), "cc.npz")

    # phase 1: first two windows, snapshotting after each
    first = ConnectedComponents(window_ms=100).run(
        _timed_stream(EDGES_T[:2]), checkpoint_path=ckpt
    )
    results1 = first.collect()
    assert str(results1[-1][0]) == "{1=[1, 2], 3=[3, 4]}"
    assert os.path.exists(ckpt)

    # phase 2: a NEW aggregation restores and continues with the rest
    second = ConnectedComponents(window_ms=100).run(
        _timed_stream(EDGES_T[2:]), checkpoint_path=ckpt
    )
    results2 = second.collect()

    # uninterrupted reference run
    full = ConnectedComponents(window_ms=100).run(_timed_stream(EDGES_T)).collect()
    assert str(results2[-1][0]) == str(full[-1][0])
    assert str(results2[-1][0]) == "{1=[1, 2, 3, 4], 5=[5, 6]}"


def test_checkpoint_restore_disabled(tmp_path):
    ckpt = os.path.join(str(tmp_path), "cc.npz")
    ConnectedComponents(window_ms=100).run(
        _timed_stream(EDGES_T[:2]), checkpoint_path=ckpt
    ).collect()
    # restore=False ignores the snapshot and starts fresh
    fresh = ConnectedComponents(window_ms=100).run(
        _timed_stream(EDGES_T[2:]), checkpoint_path=ckpt, restore=False
    ).collect()
    assert str(fresh[-1][0]) == "{2=[2, 3], 5=[5, 6]}"

"""Drift guard for the single-sourced native layer.

The canonical C++ source is the PACKAGED copy
(``gelly_streaming_tpu/native_src/edge_parser.cpp``); the repo-layout
``native/edge_parser.cpp`` is a one-``#include`` reference stub.  The two
can no longer drift because only one of them holds code — and this test
pins exactly that shape, so a well-meaning edit that re-introduces a
second hand-synced copy (the pre-ISSUE-14 state) fails tier-1 at the file
that did it.
"""

import os

from gelly_streaming_tpu.utils import native as native_mod

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
STUB = os.path.join(ROOT, "native", "edge_parser.cpp")
CANONICAL = os.path.join(
    ROOT, "gelly_streaming_tpu", "native_src", "edge_parser.cpp"
)


def test_repo_stub_is_reference_only():
    assert native_mod.stub_is_reference_only(STUB), (
        "native/edge_parser.cpp must stay a reference stub — comments plus "
        f"exactly one {native_mod.STUB_INCLUDE_LINE!r} line.  The canonical "
        "source to edit is gelly_streaming_tpu/native_src/edge_parser.cpp "
        "(the packaged copy); a second code-carrying file would be a "
        "hand-synced fork, the drift this guard exists to prevent."
    )


def test_stub_include_resolves_to_canonical():
    """The stub's include path must actually reach the canonical source
    (a rename/move that breaks the relative path would otherwise only
    surface at the next cold native build)."""
    with open(STUB, "r", encoding="utf-8") as f:
        lines = [ln.strip() for ln in f if ln.strip().startswith("#include")]
    assert lines == [native_mod.STUB_INCLUDE_LINE]
    rel = lines[0].split('"')[1]
    resolved = os.path.normpath(os.path.join(os.path.dirname(STUB), rel))
    assert os.path.samefile(resolved, CANONICAL)


def test_loader_compiles_the_canonical_source():
    """The build path must compile the packaged source (one truth for the
    binary too), and the canonical file must be the code-carrying one."""
    assert os.path.samefile(native_mod._SRC, CANONICAL)
    with open(CANONICAL, "r", encoding="utf-8") as f:
        body = f.read()
    # spot-check that the canonical copy carries the real entry points
    for symbol in ("fill_edges_range", "sort_edges_dst_src", "decode_wire_into"):
        assert symbol in body


def test_stub_guard_rejects_code_carrying_copy(tmp_path):
    fork = tmp_path / "edge_parser.cpp"
    fork.write_text(
        "// comment\n"
        f"{native_mod.STUB_INCLUDE_LINE}\n"
        "int64_t sneaky() { return 0; }\n"
    )
    assert not native_mod.stub_is_reference_only(str(fork))
    missing = tmp_path / "missing_include.cpp"
    missing.write_text("// only comments, no include\n")
    assert not native_mod.stub_is_reference_only(str(missing))

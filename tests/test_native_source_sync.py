"""Drift guard: the pip-packaging copy of the native parser must stay a
byte-identical build-time copy of the authoritative source (VERDICT r3
copy-paste note: one source of truth, guarded)."""

import os


def test_native_packaging_copy_in_sync():
    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    src = os.path.join(root, "native", "edge_parser.cpp")
    dst = os.path.join(
        root, "gelly_streaming_tpu", "native_src", "edge_parser.cpp"
    )
    with open(src, "rb") as f:
        want = f.read()
    with open(dst, "rb") as f:
        have = f.read()
    assert have == want, (
        "gelly_streaming_tpu/native_src/edge_parser.cpp has drifted from "
        "native/edge_parser.cpp — the latter is the one source of truth; "
        "run `python -m gelly_streaming_tpu.utils.native --sync`"
    )


def test_sync_helper_restores_copy(tmp_path, monkeypatch):
    from gelly_streaming_tpu.utils import native as native_mod

    assert native_mod.sync_packaging_copy() is False  # already in sync

    # drift case: the helper must restore the PACKAGING copy from the
    # authoritative source (never the other way around)
    repo = tmp_path / "repo"
    (repo / "native").mkdir(parents=True)
    pkg = repo / "pkg"
    (pkg / "native_src").mkdir(parents=True)
    (repo / "native" / "edge_parser.cpp").write_text("// authoritative v2\n")
    (pkg / "native_src" / "edge_parser.cpp").write_text("// stale v1\n")
    monkeypatch.setattr(native_mod, "_REPO_ROOT", str(repo))
    monkeypatch.setattr(native_mod, "_PKG_ROOT", str(pkg))
    assert native_mod.sync_packaging_copy() is True
    assert (
        (pkg / "native_src" / "edge_parser.cpp").read_text()
        == "// authoritative v2\n"
    )
    assert (
        (repo / "native" / "edge_parser.cpp").read_text()
        == "// authoritative v2\n"
    )
    assert native_mod.sync_packaging_copy() is False  # idempotent

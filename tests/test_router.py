"""Fleet tier data plane (ISSUE 20): the GLY1 router.

The contracts under test:

* RELAY — clients speak the unchanged frame protocol to the router;
  placed verbs land on their rendezvous backend with pipelining and the
  positional offset guard intact, and replies come back in request order
  even when consecutive frames hit different backends.
* AGGREGATION — ``status``/``metrics``/``health``/``events`` fan out to
  every live backend and merge (job-row union, summed counters,
  backend-tagged alerts/events) with per-backend truth under
  ``backends``; the router-only ``fleet`` verb exposes placement.
* TYPED FAILURE — a frame bound for a dead backend is refused
  ``rerouted`` (never a hang, never silent), and
  ``GellyClient.push_edges_resilient`` resyncs through ``out-of-sync``
  cursors without ever silently re-pushing acked edges.
* ``gelly-top --fleet`` renders the merged view with a BACKEND column
  and works with ``--json --once``.

Every test carries ``timeout_cap`` (sockets + threads throughout).
"""

import json
import socket
from contextlib import ExitStack, contextmanager

import numpy as np
import pytest

from gelly_streaming_tpu.core.config import ServerConfig, TenantConfig
from gelly_streaming_tpu.runtime import JobManager
from gelly_streaming_tpu.runtime.client import GellyClient, ServerRefused
from gelly_streaming_tpu.runtime.fleet import (
    BackendSpec,
    Fleet,
    FleetConfig,
)
from gelly_streaming_tpu.runtime.router import (
    GLYRouter,
    RouterConfig,
    _load_fleet_config,
)
from gelly_streaming_tpu.runtime.server import StreamServer

pytestmark = pytest.mark.timeout_cap(300)

CAP = 1 << 10
W = 1 << 8
B = 1 << 7
N = 4 * W


def _graph(seed: int, n: int = N, cap: int = CAP):
    rng = np.random.default_rng(seed)
    return (
        rng.integers(0, cap, n).astype(np.int32),
        rng.integers(0, cap, n).astype(np.int32),
    )


@contextmanager
def _fleet_of(n_backends: int, fleet_kw=None, server_cfg=None):
    """N in-process StreamServers behind an in-process router."""
    with ExitStack() as stack:
        servers = []
        for _ in range(n_backends):
            jm = stack.enter_context(JobManager())
            servers.append(
                stack.enter_context(
                    StreamServer(jm, server_cfg or ServerConfig())
                )
            )
        cfg = FleetConfig(
            backends=tuple(
                BackendSpec(f"b{i + 1}", "127.0.0.1", s.port)
                for i, s in enumerate(servers)
            ),
            # probing off by default: these tests drive liveness
            # explicitly so they stay deterministic
            probe_interval_s=3600.0,
            **(fleet_kw or {}),
        )
        router = stack.enter_context(GLYRouter(Fleet(cfg), RouterConfig()))
        yield servers, router


def _push_and_count(client, job, seed):
    src, dst = _graph(seed)
    client.submit(
        name=job, query="edges", capacity=CAP, window_edges=W, batch=B
    )
    client.push_edges(job, src, dst, batch=B, capacity=CAP)
    return [int(r[0]) for r in client.iter_results(job, deadline_s=120)]


# ---------------------------------------------------------------------------
# relay: placement + pipelining + offset guard through the router
# ---------------------------------------------------------------------------


def test_router_relays_jobs_across_backends_with_exact_counts():
    """One client connection, three jobs placed across two backends: every
    pipelined push relays to its placement and the per-window cumulative
    edge counts are exact — the serving contract is unchanged at the hop."""
    serial = [(i + 1) * W for i in range(N // W)]
    with _fleet_of(2) as (_servers, router):
        with GellyClient("127.0.0.1", router.port) as c:
            assert c.ping()["router"] is True
            for i, job in enumerate(("jA", "jB", "jC")):
                assert _push_and_count(c, job, seed=i) == serial
            placement = c.call({"verb": "fleet", "jobs": ["jA", "jB", "jC"]})[
                0
            ]["fleet"]["placement"]
        # rendezvous must actually spread (pinned: md5 placement is
        # deterministic, so this can never flake)
        assert set(placement.values()) == {"b1", "b2"}, placement


def test_router_preserves_offset_guard_and_expected_cursor():
    """A stale declared offset through the router is refused
    ``out-of-sync`` WITH the advertised resync cursor — the refusal is
    relayed verbatim, so fleet resync uses the same machinery as direct."""
    src, dst = _graph(3)
    with _fleet_of(1) as (_servers, router):
        with GellyClient("127.0.0.1", router.port) as c:
            c.submit(
                name="guard", query="edges", capacity=CAP, window_edges=W,
                batch=B,
            )
            c.push_edges(
                "guard", src[:W], dst[:W], batch=B, capacity=CAP, close=False
            )
            with pytest.raises(ServerRefused) as ei:
                # re-declaring offset 0 after W acked edges = a replay of
                # already-counted frames: refused, never folded twice
                c.push_edges(
                    "guard", src[:W], dst[:W], batch=B, capacity=CAP,
                    close=False,
                )
            assert ei.value.code == "out-of-sync"
            assert ei.value.details.get("expected") == W


def test_router_refuses_unknown_verb_and_missing_job():
    with _fleet_of(1) as (_servers, router):
        with GellyClient("127.0.0.1", router.port) as c:
            with pytest.raises(ServerRefused) as ei:
                c.call({"verb": "frobnicate"})
            assert ei.value.code == "unknown-verb"
            with pytest.raises(ServerRefused) as ei:
                c.call({"verb": "push", "kind": "tail", "count": 0})
            assert ei.value.code == "bad-spec"
            # the connection survives both refusals
            assert c.ping()["ok"]


# ---------------------------------------------------------------------------
# fan-out aggregation
# ---------------------------------------------------------------------------


def test_router_fanout_merges_status_metrics_events():
    from gelly_streaming_tpu.utils import metrics

    metrics.reset_job_stats()  # the registry is process-global
    with _fleet_of(2) as (_servers, router):
        with GellyClient("127.0.0.1", router.port) as c:
            for i, job in enumerate(("fanA", "fanB", "fanC")):
                _push_and_count(c, job, seed=10 + i)
            st = c.status()
            jobs = st["status"]["jobs"]
            assert set(jobs) == {
                "default/fanA", "default/fanB", "default/fanC",
            }
            # every merged row names its backend (the --fleet column)
            assert set(st["job_backend"]) == set(jobs)
            assert set(st["job_backend"].values()) == {"b1", "b2"}
            # [name]-prefixed lines from BOTH backends
            prefixes = {ln.split("]")[0] + "]" for ln in st["lines"]}
            assert prefixes == {"[b1]", "[b2]"}
            # summed server counters, per-backend truth preserved
            assert st["server"]["served_jobs"] == 3
            assert set(st["backends"]) == {"b1", "b2"}
            snap = c.metrics()
            assert set(snap["jobs"]) == set(jobs)
            total = sum(
                row.get("job_edges", 0) for row in snap["jobs"].values()
            )
            assert total == 3 * N
            evs = c.events(64)
            assert {ev["backend"] for ev in evs} == {"b1", "b2"}
            assert c.health()["jobs"] is not None
            fleet_snap = c.call({"verb": "fleet"})[0]["fleet"]
            assert set(fleet_snap["backends"]) == {"b1", "b2"}
            assert fleet_snap["standby"] is None


# ---------------------------------------------------------------------------
# typed rerouted refusal + client resync
# ---------------------------------------------------------------------------


def test_router_answers_rerouted_for_dead_backend():
    """A backend that stops answering gets its frames refused with the
    typed ``rerouted`` code naming the backend — at frame latency, via
    the registry's report_failure path, never a hang."""
    # a port that was live once and is now closed: bind, grab, release
    probe = socket.socket()
    probe.bind(("127.0.0.1", 0))
    dead_port = probe.getsockname()[1]
    probe.close()
    cfg = FleetConfig(
        backends=(BackendSpec("b1", "127.0.0.1", dead_port),),
        probe_interval_s=3600.0,
        fail_threshold=1,
    )
    with GLYRouter(Fleet(cfg), RouterConfig()) as router:
        with GellyClient("127.0.0.1", router.port) as c:
            with pytest.raises(ServerRefused) as ei:
                c.submit(name="lost", query="edges", capacity=CAP)
            assert ei.value.code == "rerouted"
            assert ei.value.details.get("backend") == "b1"
            # the router connection itself stays healthy
            assert c.ping()["ok"]


def test_resilient_push_resyncs_without_replaying_acked_edges():
    """``push_edges_resilient`` after a mid-stream connection loss: the
    client re-dials, re-declares from its stale cursor, is refused
    ``out-of-sync`` (acked edges are NEVER silently folded twice), jumps
    to the advertised cursor, and finishes with exact counts — each
    window emitted exactly once."""
    src, dst = _graph(21)
    serial = [(i + 1) * W for i in range(N // W)]
    half = N // 2
    with _fleet_of(1) as (_servers, router):
        with GellyClient("127.0.0.1", router.port) as c:
            c.submit(
                name="res", query="edges", capacity=CAP, window_edges=W,
                batch=B,
            )
            c.push_edges(
                "res", src[:half], dst[:half], batch=B, capacity=CAP,
                close=False,
            )
            # sever the connection underneath the client (the mid-push
            # kill shape: the socket dies with acked frames behind it)
            c._sock.shutdown(socket.SHUT_RDWR)
            pushed = c.push_edges_resilient(
                "res", src, dst, batch=B, capacity=CAP, start=0,
                deadline_s=60.0, backoff_s=0.05,
            )
            assert pushed == N
            counts = [int(r[0]) for r in c.iter_results("res", deadline_s=120)]
    # exactly-once emissions: the resync skipped the acked half instead
    # of re-folding it
    assert counts == serial


# ---------------------------------------------------------------------------
# gelly-top --fleet
# ---------------------------------------------------------------------------


def test_gelly_top_fleet_json_once_and_backend_column(capsys):
    from gelly_streaming_tpu.runtime import top as top_mod

    with _fleet_of(2) as (_servers, router):
        with GellyClient("127.0.0.1", router.port) as c:
            for i, job in enumerate(("tA", "tB")):
                _push_and_count(c, job, seed=30 + i)
        addr = f"127.0.0.1:{router.port}"
        assert top_mod.main(["--connect", addr, "--fleet", "--json", "--once"]) == 0
        frame = json.loads(capsys.readouterr().out)
        assert set(frame["fleet"]["backends"]) == {"b1", "b2"}
        rows = frame["jobs"]
        assert set(rows) == {"default/tA", "default/tB"}
        assert {row["backend"] for row in rows.values()} <= {"b1", "b2"}
        assert all(row["backend"] for row in rows.values())
        assert top_mod.main(["--connect", addr, "--fleet", "--once"]) == 0
        out = capsys.readouterr().out
        assert "BACKEND" in out
        assert "fleet: 2/2 backends up" in out


# ---------------------------------------------------------------------------
# console config parsing
# ---------------------------------------------------------------------------


def test_load_fleet_config_parses_backends_tenants_rebalance(tmp_path):
    conf = {
        "listen": "127.0.0.1:0",
        "replica_dir": str(tmp_path / "replica"),
        "tenants": [
            {"tenant": "t1", "token": "tok1"},
            {"tenant": "t2", "token": "tok2"},
        ],
        "backends": [
            {
                "name": "b1",
                "addr": "127.0.0.1:7421",
                "journal": str(tmp_path / "j1.jsonl"),
                "checkpoint_prefix": str(tmp_path / "ck1"),
            },
            {"name": "sb", "addr": "127.0.0.1:7429", "standby": True},
        ],
        "rebalance": {"interval_s": 1.0, "page_streak": 2},
    }
    fleet_cfg, rb = _load_fleet_config(conf)
    assert [b.name for b in fleet_cfg.backends] == ["b1", "sb"]
    assert fleet_cfg.backends[0].journal_path == str(tmp_path / "j1.jsonl")
    assert fleet_cfg.backends[1].standby is True
    assert fleet_cfg.tenant_tokens == {"t1": "tok1", "t2": "tok2"}
    assert fleet_cfg.replica_dir == str(tmp_path / "replica")
    assert rb["page_streak"] == 2
    with pytest.raises(SystemExit):
        _load_fleet_config({"backends": [{"name": "x", "addr": "nope"}]})


# ---------------------------------------------------------------------------
# token-scoped fan-out: the router forwards the CLIENT's token
# ---------------------------------------------------------------------------


def test_router_fanout_is_tenant_scoped():
    """Two tenants through one router: each sees only its own job rows in
    the merged status/metrics — the router adds aggregation, never
    disclosure (scoping stays the backend's job)."""
    cfg = ServerConfig(
        tenants=(
            TenantConfig(tenant="t1", token="tok1"),
            TenantConfig(tenant="t2", token="tok2"),
        )
    )
    with _fleet_of(
        2,
        fleet_kw={"tenant_tokens": {"t1": "tok1", "t2": "tok2"}},
        server_cfg=cfg,
    ) as (_servers, router):
        for token, job in (("tok1", "mine"), ("tok2", "theirs")):
            with GellyClient("127.0.0.1", router.port, token=token) as c:
                _push_and_count(c, job, seed=40)
        with GellyClient("127.0.0.1", router.port, token="tok1") as c:
            st = c.status()
            assert set(st["status"]["jobs"]) == {"t1/mine"}
            assert set(c.metrics()["jobs"]) == {"t1/mine"}

"""shapeflow (tier-1): the interprocedural shape-provenance prover behind
the 0-recompile guarantee.

Three layers:

* the FIXTURE CORPUS — bad_shapeflow.py seeds all three finding codes
  (UNBUCKETED at a key, UNBUCKETED through an interprocedural call,
  KEYLEAK, DTYPEDRIFT) and the good twin — same kernels, shapes rounded
  through a pow2 bucket, keys complete, dtypes pinned — scans clean;
* the REAL TREE — the SpMV pane builders and the fused-dispatch plane
  (the two hottest compile-boundary surfaces) hold zero non-grandfathered
  shapeflow findings;
* the RUNTIME CROSS-CHECK — the prover's verdict is not just a lint
  opinion: driving the seeded UNBUCKETED repro through a small
  compile_cache really recompiles (stats say so), while the bucketed twin
  over the SAME batch sizes stays at zero.
"""

import importlib.util
import os

import pytest

from gelly_streaming_tpu import analysis

CORPUS = os.path.join(os.path.dirname(__file__), "analysis_corpus")
REPO_ROOT = os.path.dirname(analysis.package_root())


def _analyze(path):
    return analysis.analyze_file(os.path.join(CORPUS, path))


def _codes(findings):
    return sorted(f.code for f in findings)


# ---------------------------------------------------------------------------
# fixture corpus


def test_corpus_shapeflow():
    findings = _analyze("bad_shapeflow.py")
    assert _codes(findings) == [
        "DTYPEDRIFT",
        "KEYLEAK",
        "UNBUCKETED",
        "UNBUCKETED",
        "UNBUCKETED",
    ]
    msgs = "\n".join(f.message for f in findings)
    # the three UNBUCKETED flavors: key element, compiled-call array
    # argument, and the interprocedural obligation at the caller
    assert "compile-cache key" in msgs
    assert "data-dependent shape passed to a compiled kernel" in msgs
    assert "_fold_for" in msgs and "parameter 'n'" in msgs
    assert "closes over local 'scale'" in msgs
    assert "bare Python scalar" in msgs
    assert _analyze("good_shapeflow.py") == []


def test_corpus_staledisable():
    findings = _analyze("bad_staledisable.py")
    assert _codes(findings) == ["STALEDISABLE"]
    assert "graft: disable=RAWJIT" in findings[0].message
    assert _analyze("good_staledisable.py") == []


def test_shapeflow_cases_invisible_to_trace_safety():
    """The acceptance proof: the seeded provenance defects are INVISIBLE
    to the intraprocedural trace-safety pass — shapeflow's lattice +
    obligation flow is the only thing standing between them and a
    recompile storm in production."""
    p5 = [analysis.load_passes()["trace-safety"]]
    findings = analysis.analyze_file(
        os.path.join(CORPUS, "bad_shapeflow.py"), p5
    )
    assert findings == [], "\n".join(f.format() for f in findings)


# ---------------------------------------------------------------------------
# real tree: the hot compile-boundary surfaces prove clean


def test_spmv_and_fused_dispatch_prove_clean():
    """ops/spmv.py (masked-semiring pane kernels) and core/aggregation.py
    (the fused-dispatch mega-fold and its wire/scan/pane builders) carry
    the densest compile boundaries in the tree: the prover must hold them
    at zero non-grandfathered findings."""
    root = analysis.package_root()
    paths = [
        os.path.join(root, "ops", "spmv.py"),
        os.path.join(root, "core", "aggregation.py"),
        os.path.join(root, "core", "stream.py"),
    ]
    pass_obj = [analysis.load_passes()["shapeflow"]]
    findings = analysis.analyze_paths(paths, pass_obj, root=REPO_ROOT)
    baseline = analysis.load_baseline(analysis.default_baseline_path())
    new, _old = analysis.apply_baseline(findings, baseline)
    assert new == [], "\n".join(f.format() for f in new)


# ---------------------------------------------------------------------------
# runtime cross-check: the static verdict matches compile_cache's meter


def _load_corpus_module(name):
    path = os.path.join(CORPUS, name + ".py")
    spec = importlib.util.spec_from_file_location(
        f"shapeflow_corpus_{name}", path
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


@pytest.fixture
def small_cache(monkeypatch):
    """A 4-entry compile cache, emptied before and after: small enough
    that the unbucketed repro's key churn forces evictions + re-traces
    within a handful of calls."""
    from gelly_streaming_tpu.core import compile_cache

    monkeypatch.setattr(compile_cache, "_CAPACITY", 4)
    compile_cache.clear()
    yield compile_cache
    compile_cache.clear()


BATCHES = [[float(i + 1) for i in range(n)] for n in range(1, 9)]


def test_unbucketed_repro_actually_recompiles(small_cache):
    """The seeded UNBUCKETED is a real defect, not a style nit: 8 distinct
    data-dependent keys cycled twice through a 4-entry cache evict and
    re-trace the same (key, signature) — the retrace guard's meter moves."""
    bad = _load_corpus_module("bad_shapeflow")
    for _ in range(2):
        for batch in BATCHES:
            bad.unbucketed_step(batch)
    stats = small_cache.stats()
    assert stats["recompiles"] > 0, stats


def test_bucketed_twin_stays_at_zero_recompiles(small_cache):
    """The good twin's fix is sufficient, not just quieter: the SAME batch
    sizes rounded through pow2_bucket collapse to <= 4 shape classes, fit
    the 4-entry cache, and never re-trace."""
    good = _load_corpus_module("good_shapeflow")
    for _ in range(2):
        for batch in BATCHES:
            good.bucketed_step(batch)
    stats = small_cache.stats()
    assert stats["recompiles"] == 0, stats
    assert stats["entries"] <= 4

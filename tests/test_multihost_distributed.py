"""REAL multi-process watermark agreement over jax.distributed.

tests/test_multihost.py exercises the lockstep transport with in-process
thread barriers; this module runs the actual production transport — TWO
OS processes forming a jax.distributed CPU cluster, one
``multihost_utils.process_allgather`` round per ingested batch
(``JaxWatermarkBoard``) — the DCN path a real multi-host TPU job uses.
Unequal batch counts exercise the END-padding protocol: the short host must
keep joining rounds until every host reports END, and both hosts must close
the identical pane-id sequence.
"""

import json
import os
import socket
import subprocess
import sys
import textwrap

import pytest

# two real jax.distributed processes over a TCP coordinator: a wedged
# barrier must fail here, not hang tier-1 (test-discipline pass gate)
pytestmark = pytest.mark.timeout_cap(600)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

_WORKER = textwrap.dedent(
    """
    import json, os, sys
    sys.path.insert(0, %(repo)r)
    import jax

    jax.config.update("jax_platforms", "cpu")
    from gelly_streaming_tpu.parallel import multihost as mh

    coord, pid = sys.argv[1], int(sys.argv[2])
    env = mh.distributed_env(
        coordinator_address=coord, num_processes=2, process_id=pid
    )
    assert (env.host_id, env.num_hosts) == (pid, 2), env

    import numpy as np

    from gelly_streaming_tpu.core.types import EdgeBatch

    # host 0 ingests windows 0..4; host 1 only 1..2 (END-padding path)
    wids = [0, 1, 2, 3, 4] if pid == 0 else [1, 2]

    def batches():
        for w in wids:
            t = np.array([w * 100 + 5], np.int64)
            yield EdgeBatch.from_arrays(
                np.array([pid * 10 + w], np.int32),
                np.array([w], np.int32),
                time=t,
            )

    board = mh.JaxWatermarkBoard()
    out = []
    for pane in mh.lockstep_tumbling_windows(
        batches(), 100, board.allgather, timeout=60.0
    ):
        out.append(
            {
                "wid": int(pane.window_id),
                "src": np.asarray(pane.src).tolist(),
            }
        )
    print("RESULT " + json.dumps(out), flush=True)
    """
)


def test_two_process_jax_distributed_lockstep(tmp_path):
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
    coord = f"127.0.0.1:{port}"
    env = dict(os.environ)
    env.pop("XLA_FLAGS", None)  # workers don't need the virtual 8-dev mesh

    # stdout/stderr go to FILES: piping would deadlock (the parent drains one
    # worker's pipes while the other blocks on a full pipe, which stalls the
    # collective both are inside)
    logs = []
    procs = []
    for pid in (0, 1):
        out_f = open(tmp_path / f"w{pid}.out", "w+")
        err_f = open(tmp_path / f"w{pid}.err", "w+")
        logs.append((out_f, err_f))
        procs.append(
            subprocess.Popen(
                [sys.executable, "-c", _WORKER % {"repo": REPO}, coord, str(pid)],
                stdout=out_f,
                stderr=err_f,
                env=env,
                text=True,
            )
        )
    outs = []
    try:
        for p in procs:
            p.wait(timeout=180)
    except subprocess.TimeoutExpired:
        for q in procs:
            q.kill()
        raise
    for p, (out_f, err_f) in zip(procs, logs):
        out_f.seek(0)
        err_f.seek(0)
        stdout, stderr = out_f.read(), err_f.read()
        out_f.close()
        err_f.close()
        if "Multiprocess computations aren't implemented" in stderr:
            import pytest

            pytest.skip(
                "this jax build's CPU backend has no multi-process "
                "collectives (jax.distributed over CPU unsupported)"
            )
        assert p.returncode == 0, stderr[-2000:]
        line = [l for l in stdout.splitlines() if l.startswith("RESULT ")][-1]
        outs.append(json.loads(line[len("RESULT ") :]))

    # identical pane-id sequences on both hosts (the lockstep contract),
    # covering the union of both hosts' windows
    assert [p["wid"] for p in outs[0]] == [p["wid"] for p in outs[1]]
    assert [p["wid"] for p in outs[0]] == [0, 1, 2, 3, 4]
    # each host's pane carries exactly its own local share
    for pid, out in enumerate(outs):
        wids = [0, 1, 2, 3, 4] if pid == 0 else [1, 2]
        for pane in out:
            expect = [pid * 10 + pane["wid"]] if pane["wid"] in wids else []
            assert pane["src"] == expect, (pid, pane)

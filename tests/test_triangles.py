"""Triangle counting tests.

Window variant mirrors WindowTrianglesITCase (19-edge timestamped dataset,
util/ExamplesTestData.java:21-34, golden TRIANGLES_RESULT); the streaming exact
variant mirrors TriangleCountTest's record-by-record counter semantics."""

import numpy as np
import pytest

from gelly_streaming_tpu.core.config import StreamConfig
from gelly_streaming_tpu.core.stream import EdgeStream
from gelly_streaming_tpu.library.triangles import (
    ExactTriangleCount,
    GLOBAL_KEY,
    window_triangles,
)

CFG = StreamConfig(vertex_capacity=16, max_degree=16)

# ExamplesTestData.TRIANGLES_DATA (:21-31): "src dst timestamp"
TRIANGLES_DATA = [
    (1, 2, 100), (1, 3, 150), (3, 2, 200), (2, 4, 250), (3, 4, 300),
    (3, 5, 350), (4, 5, 400), (4, 6, 450), (6, 5, 500), (5, 7, 550),
    (6, 7, 600), (8, 6, 650), (7, 8, 700), (7, 9, 750), (8, 9, 800),
    (10, 8, 850), (9, 10, 900), (9, 11, 950), (10, 11, 1000),
]


def test_window_triangles_golden():
    edges = [(s, d, 0, t) for s, d, t in TRIANGLES_DATA]
    stream = EdgeStream.from_collection(edges, CFG, batch_size=4, with_time=True)
    got = sorted(window_triangles(stream, 400).collect())
    # TRIANGLES_RESULT (:33-34): (2,399) (3,799) (2,1199)
    assert got == [(2, 399), (2, 1199), (3, 799)]


def test_window_triangles_no_triangles():
    edges = [(1, 2, 0, 10), (3, 4, 0, 20)]
    stream = EdgeStream.from_collection(edges, CFG, with_time=True)
    assert window_triangles(stream, 1000).collect() == [(0, 999)]


@pytest.mark.parametrize("bs", [1, 3, 7])
def test_exact_triangle_count_fixture(bs):
    # 7-edge fixture has triangles {1,2,3}, {3,4,5}, {1,3,5}
    edges = [(1, 2), (1, 3), (2, 3), (3, 4), (3, 5), (4, 5), (5, 1)]
    stream = EdgeStream.from_collection(edges, CFG, batch_size=bs)
    algo = ExactTriangleCount()
    recs = algo.run(stream).collect()
    finals = {}
    for k, c in recs:
        finals[k] = c
    assert finals[GLOBAL_KEY] == 3
    local = np.asarray(algo.final_state.local)
    assert local[1] == 2 and local[2] == 1 and local[3] == 3
    assert local[4] == 1 and local[5] == 2


def test_exact_triangle_count_ignores_duplicates():
    edges = [(1, 2), (2, 3), (1, 3), (1, 3), (2, 1)]
    stream = EdgeStream.from_collection(edges, CFG)
    algo = ExactTriangleCount()
    recs = algo.run(stream).collect()
    assert dict((k, c) for k, c in recs)[GLOBAL_KEY] == 1


def test_block_kernel_matches_scan_final_state():
    """triangle_update_block (chunk-vectorized) must reach the exact final
    state of the per-edge scan on random multigraphs with dups/self-loops."""
    import jax
    import jax.numpy as jnp

    from gelly_streaming_tpu.library.triangles import (
        init_triangle_state,
        triangle_update,
        triangle_update_block,
    )

    cfg = StreamConfig(vertex_capacity=32, max_degree=32, batch_size=128)
    rng = np.random.default_rng(17)
    for trial in range(3):
        src = rng.integers(0, 20, 128).astype(np.int32)
        dst = rng.integers(0, 20, 128).astype(np.int32)  # dups + self loops
        mask = rng.random(128) < 0.9
        s, d, m = jnp.asarray(src), jnp.asarray(dst), jnp.asarray(mask)
        scan_state, _, _ = jax.jit(triangle_update)(
            init_triangle_state(cfg), s, d, m
        )
        for chunk in (16, 64, 128):
            blk = jax.jit(
                lambda st, a, b, c: triangle_update_block(st, a, b, c, chunk)
            )(init_triangle_state(cfg), s, d, m)
            assert int(blk.global_count) == int(scan_state.global_count)
            assert np.array_equal(
                np.asarray(blk.local), np.asarray(scan_state.local)
            )
            assert np.array_equal(
                np.sort(np.asarray(blk.table.deg)),
                np.sort(np.asarray(scan_state.table.deg)),
            )


def test_block_mode_emits_running_counts():
    from gelly_streaming_tpu.library.triangles import ExactTriangleCount

    cfg = StreamConfig(vertex_capacity=16, max_degree=16, batch_size=4)
    edges = [(1, 2), (2, 3), (1, 3), (3, 4), (2, 4)]  # 2 triangles
    stream = EdgeStream.from_collection(edges, cfg, batch_size=4)
    algo = ExactTriangleCount(mode="block")
    recs = algo.run(stream).collect()
    finals = {k: v for k, v in recs}  # last write per key wins
    assert finals[-1] == 2
    assert finals[2] == 2 and finals[3] == 2  # vertices on both triangles
    assert finals[1] == 1 and finals[4] == 1


def test_pipelined_pane_counts_match_sequential():
    from gelly_streaming_tpu.library.triangles import (
        _pane_triangle_count,
        pipelined_pane_counts,
    )
    from gelly_streaming_tpu.utils.metrics import WindowLatencyRecorder

    rng = np.random.default_rng(3)
    panes = [
        (
            rng.integers(0, 64, 300).astype(np.int32),
            rng.integers(0, 64, 300).astype(np.int32),
        )
        for _ in range(5)
    ] + [(np.zeros(0, np.int32), np.zeros(0, np.int32))]
    rec = WindowLatencyRecorder()
    piped = pipelined_pane_counts(panes, recorder=rec, warmup=1)
    seq = [_pane_triangle_count(s, d) for s, d in panes]
    assert piped == seq
    assert len(rec.latencies_ms) == len(panes) - 1  # warmup pane unrecorded

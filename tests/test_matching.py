"""Greedy streaming weighted matching tests
(CentralizedWeightedMatching.java:68-108 semantics)."""

from gelly_streaming_tpu.core.config import StreamConfig
from gelly_streaming_tpu.core.stream import EdgeStream
from gelly_streaming_tpu.library.matching import CentralizedWeightedMatching

CFG = StreamConfig(vertex_capacity=16, max_degree=16)


def test_matching_scenario():
    edges = [
        (1, 2, 10),  # ADD (no collisions)
        (3, 4, 5),  # ADD
        (2, 3, 100),  # collides with both (sum 15), 100 > 30: evict both, ADD
        (1, 4, 50),  # endpoints now free: ADD
        (2, 4, 150),  # collides with (2,3,100) and (1,4,50): 150 <= 300: reject
    ]
    algo = CentralizedWeightedMatching()
    events = algo.run(EdgeStream.from_collection(edges, CFG)).collect()
    assert events == [
        ("ADD", 1, 2, 10.0),
        ("ADD", 3, 4, 5.0),
        ("REMOVE", 1, 2, 10.0),
        ("REMOVE", 3, 4, 5.0),
        ("ADD", 2, 3, 100.0),
        ("ADD", 1, 4, 50.0),
    ]
    assert algo.matched_edges(algo.final_state) == [(1, 4, 50.0), (2, 3, 100.0)]


def test_matching_rematch_same_pair():
    # Re-offering the matched pair with a big weight evicts and re-adds it.
    edges = [(1, 2, 10), (1, 2, 30)]
    algo = CentralizedWeightedMatching()
    events = algo.run(EdgeStream.from_collection(edges, CFG)).collect()
    assert events == [
        ("ADD", 1, 2, 10.0),
        ("REMOVE", 1, 2, 10.0),
        ("ADD", 1, 2, 30.0),
    ]


def test_matching_weight_not_double_counted_for_same_edge():
    # (1,2,25) vs matched (1,2,10): sum must be 10 (one collision), not 20.
    edges = [(1, 2, 10), (1, 2, 25)]
    algo = CentralizedWeightedMatching()
    events = algo.run(EdgeStream.from_collection(edges, CFG)).collect()
    assert ("ADD", 1, 2, 25.0) in events

"""Binned + compressed ingest (ISSUE 6): end-to-end equivalence + guards.

The destination-binned layout and the BDV compressed wire format are
cfg-gated (``binned_ingest`` / ``wire_compress``, env twins) with the
arrival-order uncompressed layout as the equivalence oracle.  These tests
pin:

  * bit-identical emissions for CC and the degree summary over the wire
    fast path, the windowed/superbatch/async pane planes, and the sharded
    mesh planes, with binning/compression on vs the oracle;
  * checkpoint/resume parity on the compressed fast path;
  * ``parallel_host_route`` == ``host_route`` (the keyBy moved onto the
    ingest pool), including pow2 bin-arena capacities (the retrace-guard
    satellite);
  * zero recompiles across same-shape compressed batches;
  * wire metrics counters; config/env validation.
"""

import os

import numpy as np
import pytest

jax = pytest.importorskip("jax")

from gelly_streaming_tpu.core.config import StreamConfig  # noqa: E402
from gelly_streaming_tpu.core.stream import EdgeStream  # noqa: E402
from gelly_streaming_tpu.io import ingest, wire  # noqa: E402
from gelly_streaming_tpu.library.connected_components import (  # noqa: E402
    ConnectedComponents,
)
from gelly_streaming_tpu.library.degree_distribution import (  # noqa: E402
    DegreeDistributionSummary,
)
from gelly_streaming_tpu.utils import metrics  # noqa: E402

CAP = 1 << 12
N = 1 << 13
BATCH = 1 << 10


def _edges(seed=0, n=N, cap=CAP):
    rng = np.random.default_rng(seed)
    # mixed skew: hub-heavy dsts exercise long bins, the uniform half
    # exercises sparse ones
    half = n // 2
    src = rng.integers(0, cap, n).astype(np.int32)
    dst = np.concatenate(
        [
            rng.integers(0, cap, half),
            (cap * rng.random(n - half) ** 4).astype(np.int64) % cap,
        ]
    ).astype(np.int32)
    return src, dst


def _leaves(rec):
    out = []
    for x in rec:
        if hasattr(x, "parent"):
            out += [np.asarray(x.parent), np.asarray(x.seen)]
        else:
            out += [np.asarray(leaf) for leaf in jax.tree.leaves(x)]
    return out


def _assert_same(ref, got, label):
    assert len(ref) == len(got), (label, len(ref), len(got))
    for a, b in zip(ref, got):
        la, lb = _leaves(a), _leaves(b)
        assert len(la) == len(lb), label
        for x, y in zip(la, lb):
            assert np.array_equal(x, y), label


def _run(agg_cls, src, dst, **cfg_kw):
    cfg = StreamConfig(vertex_capacity=CAP, batch_size=BATCH, **cfg_kw)
    return list(agg_cls().run(EdgeStream.from_arrays(src, dst, cfg)))


@pytest.mark.parametrize("agg_cls", [ConnectedComponents, DegreeDistributionSummary])
@pytest.mark.timeout_cap(240)
def test_fast_path_emissions_match_oracle(agg_cls):
    src, dst = _edges()
    ref = _run(agg_cls, src, dst)
    for label, kw in [
        ("binned", dict(binned_ingest=1)),
        ("compressed", dict(wire_compress=1)),
        ("compressed+superbatch", dict(wire_compress=1, superbatch=4)),
    ]:
        _assert_same(ref, _run(agg_cls, src, dst, **kw), label)


@pytest.mark.parametrize("agg_cls", [ConnectedComponents, DegreeDistributionSummary])
@pytest.mark.timeout_cap(240)
def test_windowed_fast_path_running_emissions_match(agg_cls):
    """ingest_window_edges keeps the stream on the fast path with running
    emissions: one record per window, identical with compression on."""
    src, dst = _edges(1)
    ref = _run(agg_cls, src, dst, ingest_window_edges=BATCH)
    got = _run(agg_cls, src, dst, ingest_window_edges=BATCH, wire_compress=1)
    assert len(ref) == N // BATCH
    _assert_same(ref, got, "windowed-compressed")


@pytest.mark.parametrize("agg_cls", [ConnectedComponents, DegreeDistributionSummary])
@pytest.mark.timeout_cap(300)
def test_pane_planes_match_oracle(agg_cls):
    """Collection-source (pane plane) streams: sync, superbatch, and async
    windowed planes bin panes on the pack thread — same emissions."""
    rng = np.random.default_rng(2)
    edges = [
        (int(s), int(d))
        for s, d in zip(rng.integers(0, CAP, 4096), rng.integers(0, CAP, 4096))
    ]

    def run(**kw):
        cfg = StreamConfig(
            vertex_capacity=CAP,
            batch_size=256,
            ingest_window_edges=512,
            **kw,
        )
        st = EdgeStream.from_collection(edges, cfg, batch_size=256)
        return list(agg_cls().run(st))

    ref = run()
    for label, kw in [
        ("binned", dict(binned_ingest=1)),
        ("binned+superbatch", dict(binned_ingest=1, superbatch=4)),
        ("binned+async", dict(binned_ingest=1, async_windows=2)),
    ]:
        _assert_same(ref, run(**kw), label)


@pytest.mark.parametrize("agg_cls", [ConnectedComponents, DegreeDistributionSummary])
@pytest.mark.timeout_cap(300)
def test_sharded_planes_match_oracle(agg_cls):
    """Owner-sharded AND replicated mesh planes consume binned batches with
    unchanged emissions (binned rows stay sorted per shard; the keyBy runs
    on the ingest pool)."""
    if len(jax.devices()) < 2:
        pytest.skip("needs >= 2 devices")
    src, dst = _edges(3)

    def run(**kw):
        cfg = StreamConfig(
            vertex_capacity=CAP, batch_size=BATCH, num_shards=2, **kw
        )
        return list(agg_cls().run(EdgeStream.from_arrays(src, dst, cfg)))

    ref = run()
    _assert_same(ref, run(binned_ingest=1), "sharded-binned")
    _assert_same(ref, run(wire_compress=1), "sharded-compress-knob")
    repl = run(sharded_state=0)
    _assert_same(repl, run(sharded_state=0, binned_ingest=1), "replicated-binned")


@pytest.mark.timeout_cap(240)
def test_compressed_checkpoint_resume(tmp_path):
    """Positional checkpoints ride the compressed fast path unchanged:
    a fresh run resuming from a mid-stream snapshot re-emits the same
    final summary."""
    src, dst = _edges(4)
    path = str(tmp_path / "ckpt")

    def run(restore):
        cfg = StreamConfig(
            vertex_capacity=CAP,
            batch_size=BATCH,
            wire_compress=1,
            wire_checkpoint_batches=2,
        )
        stream = EdgeStream.from_arrays(src, dst, cfg)
        return list(
            ConnectedComponents().run(
                stream, checkpoint_path=path, restore=restore
            )
        )

    ref = run(restore=False)
    resumed = run(restore=True)  # done-snapshot: re-emit without refolding
    _assert_same(ref, resumed, "resume")


@pytest.mark.timeout_cap(240)
def test_compressed_zero_recompiles_across_same_shape_batches():
    """Same-regime compressed batches reuse ONE decode+fold executable:
    a second full run mints zero recompiles (and zero compiles)."""
    from gelly_streaming_tpu.core import compile_cache

    src, dst = _edges(5)

    def run():
        return _run(ConnectedComponents, src, dst, wire_compress=1)

    first = run()  # compiles land here
    compile_cache.reset_stats()
    _assert_same(first, run(), "rerun")
    stats = compile_cache.stats()
    assert stats["recompiles"] == 0
    assert stats["compiles"] == 0


@pytest.mark.timeout_cap(240)
def test_skewed_bin_arenas_keep_pow2_shapes():
    """The retrace-guard satellite: routed bin arenas pow2-bucket their
    capacity, so panes of different skew resolve to the same compiled
    shapes — occupancies within one pow2 bucket share arena capacity."""
    rng = np.random.default_rng(6)
    caps = set()
    for skew in (1, 2, 4, 6):
        src = rng.integers(0, CAP, 1 << 14).astype(np.int32)
        dst = ((CAP * rng.random(1 << 14) ** skew).astype(np.int64) % CAP).astype(
            np.int32
        )
        routed = ingest.parallel_host_route(src, dst, 4, key="dst", workers=2)
        cap = routed.src.shape[1]
        assert cap & (cap - 1) == 0, "bin arena capacity must be pow2"
        caps.add(cap)
    # skews differ wildly but capacities collapse to a handful of buckets
    assert len(caps) <= 3, caps


def test_parallel_host_route_matches_serial():
    from gelly_streaming_tpu.parallel import routing

    rng = np.random.default_rng(7)
    for n, shards, key in [(0, 2, "src"), (100, 3, "dst"), (1 << 15, 4, "src")]:
        src = rng.integers(0, CAP, n).astype(np.int32)
        dst = ((CAP * rng.random(n) ** 3).astype(np.int64) % CAP).astype(np.int32)
        serial = routing.host_route(src, dst, shards, key=key)
        par = ingest.parallel_host_route(src, dst, shards, key=key, workers=2)
        assert par.src.shape == serial.src.shape
        assert np.array_equal(par.src, serial.src)
        assert np.array_equal(par.dst, serial.dst)
        assert np.array_equal(par.mask, serial.mask)


@pytest.mark.timeout_cap(240)
def test_wire_metrics_counters():
    src, dst = _edges(8)
    metrics.reset_wire_stats()
    _run(ConnectedComponents, src, dst, wire_compress=1)
    w = metrics.wire_stats()
    assert w["wire_edges_total"] == N
    assert w["wire_batches"] == N // BATCH
    assert w["wire_raw_bytes_total"] == 8 * N
    assert 0 < w["wire_bytes_total"] < 8 * N
    assert w["wire_compress_ratio"] > 1.0
    assert w["wire_bytes_per_edge"] < 8.0
    assert w["wire_bin_occupancy_hwm"] >= 1
    metrics.reset_wire_stats()
    assert metrics.wire_stats()["wire_bytes_total"] == 0


def test_config_validation():
    with pytest.raises(ValueError, match="binned_ingest"):
        StreamConfig(binned_ingest=2)
    with pytest.raises(ValueError, match="wire_compress"):
        StreamConfig(wire_compress=-2)
    with pytest.raises(ValueError, match="binned"):
        StreamConfig(wire_compress=1, binned_ingest=0)
    with pytest.raises(ValueError, match="2\\^28"):
        StreamConfig(wire_compress=1, vertex_capacity=1 << 29)


def test_env_switch_and_bad_spelling(monkeypatch):
    cfg = StreamConfig(vertex_capacity=CAP)
    monkeypatch.delenv("GELLY_WIRE_COMPRESS", raising=False)
    monkeypatch.delenv("GELLY_BINNED_INGEST", raising=False)
    assert not wire.resolve_wire_compress(cfg)
    assert not wire.resolve_binned_ingest(cfg)
    monkeypatch.setenv("GELLY_WIRE_COMPRESS", "1")
    assert wire.resolve_wire_compress(cfg)
    assert wire.resolve_binned_ingest(cfg)  # compression implies binning
    monkeypatch.setenv("GELLY_WIRE_COMPRESS", "definitely")
    with pytest.raises(ValueError, match="GELLY_WIRE_COMPRESS"):
        wire.resolve_wire_compress(cfg)
    # explicit config wins over the env var
    monkeypatch.setenv("GELLY_WIRE_COMPRESS", "0")
    assert wire.resolve_wire_compress(
        StreamConfig(vertex_capacity=CAP, wire_compress=1)
    )
    # ... in BOTH directions: an explicit binned_ingest=0 pins the
    # arrival-order oracle even when the ambient env asks for compression
    # (compression implies binning, so it cannot ride either)
    monkeypatch.setenv("GELLY_WIRE_COMPRESS", "1")
    pinned = StreamConfig(vertex_capacity=CAP, binned_ingest=0)
    assert not wire.resolve_binned_ingest(pinned)
    assert not wire.resolve_wire_compress(pinned)


def test_order_sensitive_descriptor_refuses_forced_binning():
    """Explicit binned_ingest/wire_compress on an order-sensitive fold is a
    loud error; the ambient env switch quietly stays on the oracle."""

    class OrderSensitive(DegreeDistributionSummary):
        order_free = False

    src, dst = _edges(9, n=256)
    cfg = StreamConfig(vertex_capacity=CAP, batch_size=128, wire_compress=1)
    with pytest.raises(ValueError, match="order-free"):
        list(OrderSensitive().run(EdgeStream.from_arrays(src, dst, cfg)))
    os.environ["GELLY_WIRE_COMPRESS"] = "1"
    try:
        cfg2 = StreamConfig(vertex_capacity=CAP, batch_size=128)
        ref_env = list(
            OrderSensitive().run(EdgeStream.from_arrays(src, dst, cfg2))
        )
    finally:
        del os.environ["GELLY_WIRE_COMPRESS"]
    ref = list(OrderSensitive().run(EdgeStream.from_arrays(src, dst, cfg2)))
    _assert_same(ref, ref_env, "env-quiet-fallback")

"""Checkpointing ON the packed-wire fast path (VERDICT r2 item 2).

Round 2 made the wire fast path and checkpointing mutually exclusive; the
reference checkpoints its Merger inside the full-speed pipeline
(SummaryAggregation.java:127-135).  These tests pin the composed behavior:
positional snapshots every N wire batches, in-process crash + resume
equivalence, a REAL process SIGKILL mid-stream with resume from disk, and
exactly-once fold state proven by a non-idempotent descriptor.
"""

import os
import signal
import subprocess
import sys
import textwrap

import numpy as np
import pytest

from gelly_streaming_tpu.core.config import StreamConfig
from gelly_streaming_tpu.core.stream import EdgeStream
from gelly_streaming_tpu.library.connected_components import ConnectedComponents

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _edges(n=2048, c=128, seed=5):
    rng = np.random.default_rng(seed)
    return (
        rng.integers(0, c, n).astype(np.int32),
        rng.integers(0, c, n).astype(np.int32),
    )


def _cfg(tmp, every=4):
    return StreamConfig(
        vertex_capacity=128, batch_size=64, wire_checkpoint_batches=every
    )


class _Crash(RuntimeError):
    pass


def test_wire_checkpoint_crash_and_resume_in_process(tmp_path, monkeypatch):
    src, dst = _edges()
    cfg = _cfg(tmp_path)
    path = str(tmp_path / "ck")
    clean = (
        EdgeStream.from_arrays(src, dst, cfg)
        .aggregate(ConnectedComponents())
        .collect()
    )

    # crash after the 2nd snapshot (8 of 32 batches folded)
    import gelly_streaming_tpu.utils.checkpoint as ckpt

    real_save = ckpt.save_state
    saves = []

    def crashing_save(p, state):
        real_save(p, state)
        saves.append(p)
        if len(saves) == 2:
            raise _Crash()

    monkeypatch.setattr(ckpt, "save_state", crashing_save)
    agg = ConnectedComponents()
    with pytest.raises(_Crash):
        EdgeStream.from_arrays(src, dst, cfg).aggregate(
            agg, checkpoint_path=path
        ).collect()
    monkeypatch.setattr(ckpt, "save_state", real_save)

    # resume from disk: the source replays from the start, folded batches are
    # skipped by position, and the final components match the clean run
    snap = ckpt.load_state(path, agg._wire_checkpoint_like(
        EdgeStream.from_arrays(src, dst, cfg)
    ))
    assert int(snap["next_batch"]) == 8 and not bool(snap["done"])
    resumed = (
        EdgeStream.from_arrays(src, dst, cfg)
        .aggregate(ConnectedComponents(), checkpoint_path=path)
        .collect()
    )
    assert resumed[0][0].components() == clean[0][0].components()


def test_wire_checkpoint_done_reemits_without_refolding(tmp_path, monkeypatch):
    src, dst = _edges(n=512)
    cfg = _cfg(tmp_path)
    path = str(tmp_path / "ck")
    first = (
        EdgeStream.from_arrays(src, dst, cfg)
        .aggregate(ConnectedComponents(), checkpoint_path=path)
        .collect()
    )
    # a completed stream restores as done=True: the record re-emits from the
    # snapshot alone — no prefetcher is ever constructed
    from gelly_streaming_tpu.io import wire

    def boom(*a, **k):
        raise AssertionError("resume of a done stream must not refold")

    # patch BOTH prefetcher entry points: the array-backed fast path builds
    # the generic Prefetcher (superbatch-aware grouping), older paths the
    # WirePrefetcher — the sentinel must fire whichever a regression uses
    monkeypatch.setattr(wire, "WirePrefetcher", boom)
    monkeypatch.setattr(wire, "Prefetcher", boom)
    again = (
        EdgeStream.from_arrays(src, dst, cfg)
        .aggregate(ConnectedComponents(), checkpoint_path=path)
        .collect()
    )
    assert again[0][0].components() == first[0][0].components()


_CHILD = textwrap.dedent(
    """
    import os, signal, sys
    os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=1"
    sys.path.insert(0, {repo!r})
    import jax
    jax.config.update("jax_platforms", "cpu")
    import numpy as np
    import jax.numpy as jnp
    from gelly_streaming_tpu.core.aggregation import SummaryBulkAggregation
    from gelly_streaming_tpu.core.config import StreamConfig
    from gelly_streaming_tpu.core.stream import EdgeStream

    class EdgeCount(SummaryBulkAggregation):
        # NON-idempotent fold: re-folding any batch after a resume would
        # overcount, so the final value proves exactly-once state
        def initial_state(self, cfg):
            return jnp.zeros((), jnp.int32)

        def update(self, state, src, dst, val, mask):
            return state + jnp.sum(mask.astype(jnp.int32))

        def combine(self, a, b):
            return a + b

    kill_after = int(os.environ.get("KILL_AFTER_SAVES", "0"))
    if kill_after:
        import gelly_streaming_tpu.utils.checkpoint as ckpt
        real = ckpt.save_state
        n = [0]
        def hooked(p, s):
            real(p, s)
            n[0] += 1
            if n[0] >= kill_after:
                os.kill(os.getpid(), signal.SIGKILL)  # no cleanup, no atexit
        ckpt.save_state = hooked

    rng = np.random.default_rng(5)
    src = rng.integers(0, 128, 4096).astype(np.int32)
    dst = rng.integers(0, 128, 4096).astype(np.int32)
    cfg = StreamConfig(
        vertex_capacity=128, batch_size=64, wire_checkpoint_batches=4
    )
    out = (
        EdgeStream.from_arrays(src, dst, cfg)
        .aggregate(EdgeCount(), checkpoint_path={ckpt_path!r})
        .collect()
    )
    print("FINAL_COUNT", int(out[0][0]))
    """
)


@pytest.mark.timeout_cap(600)
def test_wire_checkpoint_sigkill_and_resume_subprocess(tmp_path):
    """SIGKILL the process mid-stream, resume from the on-disk snapshot: the
    non-idempotent edge count must come out exact (no batch folded twice or
    dropped)."""
    ckpt_path = str(tmp_path / "proc_ck")
    script = tmp_path / "child.py"
    script.write_text(_CHILD.format(repo=REPO, ckpt_path=ckpt_path))

    env = dict(os.environ, KILL_AFTER_SAVES="3")
    first = subprocess.run(
        [sys.executable, str(script)], env=env, capture_output=True, timeout=300
    )
    assert first.returncode == -signal.SIGKILL, (
        first.returncode,
        first.stdout,
        first.stderr,
    )
    assert os.path.exists(ckpt_path + ".npz"), "snapshot must survive the kill"

    env.pop("KILL_AFTER_SAVES")
    second = subprocess.run(
        [sys.executable, str(script)], env=env, capture_output=True, timeout=300
    )
    assert second.returncode == 0, second.stderr.decode()
    assert b"FINAL_COUNT 4096" in second.stdout, second.stdout


def test_wire_resume_from_legacy_windowed_snapshot(tmp_path):
    """A snapshot written by the pre-wire-checkpoint revision (windowed merge
    loop layout) must still resume: done -> re-emit, else re-fold cleanly."""
    import gelly_streaming_tpu.utils.checkpoint as ckpt

    src, dst = _edges(n=512)
    cfg = _cfg(tmp_path)
    path = str(tmp_path / "legacy")
    agg = ConnectedComponents()
    clean = (
        EdgeStream.from_arrays(src, dst, cfg).aggregate(ConnectedComponents()).collect()
    )

    # done legacy snapshot: the global pane finished under the old layout
    folded = agg.initial_state(cfg)
    # fold the whole stream once to get a real summary pytree
    import jax.numpy as jnp

    folded = agg._update_j(
        folded,
        jnp.asarray(src),
        jnp.asarray(dst),
        None,
        jnp.ones((len(src),), bool),
    )
    ckpt.save_state(
        path,
        {
            "summary": folded,
            "has_summary": np.full((), True, bool),
            "last_window": np.full((), -1, np.int64),
            "global_done": np.full((), True, bool),
        },
    )
    reemitted = (
        EdgeStream.from_arrays(src, dst, cfg)
        .aggregate(ConnectedComponents(), checkpoint_path=path)
        .collect()
    )
    assert reemitted[0][0].components() == clean[0][0].components()

    # not-done legacy snapshot: position doesn't map -> full re-fold
    ckpt.save_state(
        path,
        {
            "summary": agg.initial_state(cfg),
            "has_summary": np.full((), False, bool),
            "last_window": np.full((), -1, np.int64),
            "global_done": np.full((), False, bool),
        },
    )
    refolded = (
        EdgeStream.from_arrays(src, dst, cfg)
        .aggregate(ConnectedComponents(), checkpoint_path=path)
        .collect()
    )
    assert refolded[0][0].components() == clean[0][0].components()


def test_wire_checkpoint_resumes_across_encodings(tmp_path):
    """The snapshot stores the fold carry + batch position — both encoding
    agnostic — so a checkpoint written under the plain wire may resume under
    EF40 (and the exactly-once count still proves no batch is lost/refolded)."""
    import gelly_streaming_tpu.utils.checkpoint as ckpt
    import jax.numpy as jnp

    from gelly_streaming_tpu.core.aggregation import SummaryBulkAggregation

    class EdgeCount(SummaryBulkAggregation):
        order_free = True  # counting is order-free; EF40-eligible

        def initial_state(self, cfg):
            return jnp.zeros((), jnp.int32)

        def update(self, state, src, dst, val, mask):
            return state + jnp.sum(mask.astype(jnp.int32))

        def combine(self, a, b):
            return a + b

    src, dst = _edges(n=1024)
    path = str(tmp_path / "xenc")
    plain = StreamConfig(
        vertex_capacity=128, batch_size=64, wire_checkpoint_batches=4,
        wire_encoding="plain",
    )
    real_save = ckpt.save_state
    saves = []

    def crashing_save(p, state):
        real_save(p, state)
        saves.append(p)
        if len(saves) == 2:
            raise _Crash()

    ckpt.save_state = crashing_save
    try:
        with pytest.raises(_Crash):
            EdgeStream.from_arrays(src, dst, plain).aggregate(
                EdgeCount(), checkpoint_path=path
            ).collect()
    finally:
        ckpt.save_state = real_save

    ef = StreamConfig(
        vertex_capacity=128, batch_size=64, wire_checkpoint_batches=4,
        wire_encoding="ef40",
    )
    out = (
        EdgeStream.from_arrays(src, dst, ef)
        .aggregate(EdgeCount(), checkpoint_path=path)
        .collect()
    )
    assert int(out[0][0]) == 1024  # exactly-once across the encoding switch


def test_wire_checkpoint_async_writer_backpressure(tmp_path, monkeypatch):
    """Snapshots are written OFF the fold thread (async barrier-snapshot
    analog); a slow sink must backpressure snapshots without corrupting the
    final state or losing the terminal snapshot."""
    import time

    src, dst = _edges()
    cfg = _cfg(tmp_path, every=2)  # 16 snapshots over 32 batches
    path = str(tmp_path / "ck")

    import gelly_streaming_tpu.utils.checkpoint as ckpt

    real_save = ckpt.save_state
    calls = []

    def slow_save(p, state):
        time.sleep(0.02)  # slower than the fold produces snapshots
        calls.append(int(state["next_batch"]))
        real_save(p, state)

    monkeypatch.setattr(ckpt, "save_state", slow_save)
    out = (
        EdgeStream.from_arrays(src, dst, cfg)
        .aggregate(ConnectedComponents(), checkpoint_path=path)
        .collect()
    )
    monkeypatch.setattr(ckpt, "save_state", real_save)
    # every snapshot position is monotonically increasing and the terminal
    # snapshot (done=True, position 32) landed despite the slow sink
    assert calls == sorted(calls)
    assert calls[-1] == 32
    from gelly_streaming_tpu.utils.checkpoint import load_state

    agg = ConnectedComponents()
    stream = EdgeStream.from_arrays(src, dst, cfg)
    snap = load_state(path, agg._wire_checkpoint_like(stream))
    assert bool(snap["done"])
    clean = (
        EdgeStream.from_arrays(src, dst, cfg)
        .aggregate(ConnectedComponents())
        .collect()
    )
    np.testing.assert_array_equal(
        np.asarray(snap["summary"].parent), np.asarray(clean[-1][0].parent)
    )

"""API-parity tests for the remaining GraphStream surface:
get_edges, build_neighborhood, generic keyed_aggregate, global_aggregate
(GraphStream.java:43-140 / SimpleEdgeStream.java:489-560)."""

import jax.numpy as jnp
import numpy as np

from gelly_streaming_tpu.core.config import StreamConfig
from gelly_streaming_tpu.core.stream import EdgeStream
from gelly_streaming_tpu.ops import segments

from fixtures import LONG_LONG_EDGES, assert_lines, long_long_stream

CFG = StreamConfig(vertex_capacity=16, max_degree=16)


def test_get_edges():
    recs = long_long_stream().get_edges().collect()
    assert sorted(recs) == sorted(LONG_LONG_EDGES)


def test_build_neighborhood_undirected():
    # SimpleEdgeStream.java:531-560 (directed=false: undirected adjacency).
    # batch_size=1 recovers the reference's exact per-edge TreeSet trace.
    recs = (
        EdgeStream.from_collection([(1, 2), (1, 3), (2, 3)], CFG, batch_size=1)
        .build_neighborhood(directed=False, mode="trace")
        .collect()
    )
    # each original edge contributes both directions (undirected() doubling)
    assert recs[0] == (1, 2, (2,))
    assert recs[1] == (2, 1, (1,))
    assert (1, 3, (2, 3)) in recs
    # final adjacency of vertex 2 contains both 1 and 3
    assert recs[-1] == (3, 2, (1, 2))


def test_build_neighborhood_directed():
    recs = (
        EdgeStream.from_collection([(1, 2), (1, 3)], CFG, batch_size=1)
        .build_neighborhood(directed=True, mode="trace")
        .collect()
    )
    assert recs == [(1, 2, (2,)), (1, 3, (2, 3))]


def test_keyed_aggregate_degree_equivalent():
    # Rebuild the degree stream through the generic keyed aggregation
    # (the reference implements getDegrees exactly this way,
    # SimpleEdgeStream.java:413-415 via aggregate()).
    def edge_expand(src, dst, val):
        keys = jnp.stack([src, dst])  # [2, B]
        return keys, jnp.ones_like(keys)

    def state_init(cfg):
        return jnp.zeros((cfg.vertex_capacity,), jnp.int32)

    def vertex_update(counts, keys, vals, mask):
        rank = segments.occurrence_rank(keys, mask)
        emitted = counts[keys] + rank + 1
        counts = counts.at[jnp.where(mask, keys, 0)].add(mask.astype(jnp.int32))
        return counts, emitted, mask

    out = long_long_stream().keyed_aggregate(edge_expand, state_init, vertex_update)
    assert_lines(
        out.lines(),
        "1,1\n1,2\n1,3\n2,1\n2,2\n3,1\n3,2\n3,3\n3,4\n4,1\n4,2\n5,1\n5,2\n5,3",
    )


def test_global_aggregate_edge_count():
    # numberOfEdges through the generic centralized aggregation
    # (SimpleEdgeStream.java:388-404 analog).
    def update(total, batch):
        return total + batch.num_valid()

    out = long_long_stream(batch_size=2).global_aggregate(
        update, lambda cfg: jnp.zeros((), jnp.int32), lambda s: int(s)
    )
    assert out.collect() == [(2,), (4,), (6,), (7,)]


def test_global_aggregate_change_dedup():
    # a constant result stream emits exactly once
    out = long_long_stream(batch_size=2).global_aggregate(
        lambda s, b: s, lambda cfg: jnp.zeros((), jnp.int32), lambda s: int(s)
    )
    assert out.collect() == [(0,)]


def test_build_neighborhood_block_mode_matches_trace():
    """Default block emission: device-sorted padded rows; the trace mode's
    tuples must be recoverable row-for-row (VERDICT r2 weak #5)."""
    edges = [(1, 2), (1, 3), (2, 3), (3, 4)]
    trace = (
        EdgeStream.from_collection(edges, CFG, batch_size=2)
        .build_neighborhood(directed=False, mode="trace")
        .collect()
    )
    blocks = list(
        EdgeStream.from_collection(edges, CFG, batch_size=2)
        .build_neighborhood(directed=False)
        .blocks()
    )
    rebuilt = []
    for blk in blocks:
        s_c, d_c, rows_c, deg_c = blk.columns
        for i in range(blk.num_records):
            rebuilt.append(
                (
                    int(s_c[i]),
                    int(d_c[i]),
                    tuple(int(x) for x in rows_c[i][: deg_c[i]]),
                )
            )
    assert rebuilt == trace

"""Per-process checkpointing for the sharded streaming wire fold on a REAL
2-process jax.distributed CPU cluster: kill mid-stream, resume from each
host's own shard snapshot with a poisoned replay prefix — matching final
components prove the restored per-process carries were used."""

import json
import os
import socket
import subprocess
import sys
import textwrap

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

_WORKER = textwrap.dedent(
    """
    import json, os, sys
    sys.path.insert(0, %(repo)r)
    import jax

    jax.config.update("jax_platforms", "cpu")
    try:
        jax.config.update("jax_num_cpu_devices", 4)
    except AttributeError:  # older jax: pre-init XLA flag instead
        os.environ["XLA_FLAGS"] = (
            os.environ.get("XLA_FLAGS", "")
            + " --xla_force_host_platform_device_count=4"
        ).strip()

    coord, pid, phase, ckpt = (
        sys.argv[1], int(sys.argv[2]), sys.argv[3], sys.argv[4]
    )
    from gelly_streaming_tpu.parallel import multihost as mh

    mh.distributed_env(coordinator_address=coord, num_processes=2, process_id=pid)
    assert len(jax.devices()) == 8

    import numpy as np

    from gelly_streaming_tpu.core.config import StreamConfig
    from gelly_streaming_tpu.core.stream import EdgeStream
    from gelly_streaming_tpu.library.connected_components import ConnectedComponents

    C = 256
    rng = np.random.default_rng(31)
    src = rng.integers(0, C, 512).astype(np.int32)
    dst = rng.integers(0, C, 512).astype(np.int32)
    use_src = src.copy()
    if phase == "resume":
        # poison the WHOLE replay: every group is covered by the crash
        # run's last positional snapshot, so only the restored per-process
        # carries can still produce the true labels
        use_src[:] = 0
    # batch 32 over 8 shards -> row_len 4, 128 rows, 16 groups; snapshot
    # every 32 rows = every 4 groups
    cfg = StreamConfig(
        vertex_capacity=C, batch_size=32, num_shards=8,
        wire_checkpoint_batches=32,
    )
    agg = ConnectedComponents()
    out = EdgeStream.from_arrays(use_src, dst, cfg).aggregate(
        agg, checkpoint_path=ckpt
    )
    if phase == "crash":
        # the streaming fold yields once at stream end, AFTER all
        # mid-stream positional snapshots but BEFORE the final done-save;
        # consuming that one record and exiting abandons the generator at
        # the yield, so the last snapshot on disk is positional (not done)
        # — the crash-between-emit-and-final-save case
        it = iter(out)
        next(it)
        from gelly_streaming_tpu.utils.checkpoint import per_process_file
        assert os.path.exists(per_process_file(ckpt)), per_process_file(ckpt)
        print("RESULT " + json.dumps({"crashed": True}), flush=True)
        sys.exit(0)
    res = list(out)
    comps = res[-1][0].components()
    print("RESULT " + json.dumps({"comps": sorted(
        tuple(sorted(v)) for v in comps.values()
    )}), flush=True)
    """
)


def _run_pair(tmp_path, phase, ckpt):
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
    coord = f"127.0.0.1:{port}"
    env = dict(os.environ)
    env.pop("XLA_FLAGS", None)
    procs, logs = [], []
    for pid in (0, 1):
        out_f = open(tmp_path / f"{phase}{pid}.out", "w+")
        err_f = open(tmp_path / f"{phase}{pid}.err", "w+")
        logs.append((out_f, err_f))
        procs.append(
            subprocess.Popen(
                [
                    sys.executable, "-c", _WORKER % {"repo": REPO},
                    coord, str(pid), phase, ckpt,
                ],
                stdout=out_f, stderr=err_f, env=env, text=True,
            )
        )
    outs = []
    try:
        for p in procs:
            p.wait(timeout=240)
    except subprocess.TimeoutExpired:
        for q in procs:
            q.kill()
        raise
    for p, (out_f, err_f) in zip(procs, logs):
        out_f.seek(0)
        err_f.seek(0)
        stdout, stderr = out_f.read(), err_f.read()
        out_f.close()
        err_f.close()
        if "Multiprocess computations aren't implemented" in stderr:
            import pytest

            pytest.skip(
                "this jax build's CPU backend has no multi-process "
                "collectives (jax.distributed over CPU unsupported)"
            )
        assert p.returncode == 0, stderr[-3000:]
        line = [l for l in stdout.splitlines() if l.startswith("RESULT ")][-1]
        outs.append(json.loads(line[len("RESULT "):]))
    return outs


def test_mesh_wire_fold_multiprocess_resume(tmp_path):
    """Kill after the emission (before the final done-save), resume over a
    fully poisoned replay: the restored per-process carries must reproduce
    the TRUE stream's components exactly."""
    import numpy as np

    ckpt = str(tmp_path / "meshwire.npz")
    crash = _run_pair(tmp_path, "crash", ckpt)
    assert all(o == {"crashed": True} for o in crash)

    resumed = _run_pair(tmp_path, "resume", ckpt)
    assert resumed[0] == resumed[1]

    C = 256
    rng = np.random.default_rng(31)
    src = rng.integers(0, C, 512).astype(np.int64)
    dst = rng.integers(0, C, 512).astype(np.int64)
    parent = np.arange(C)

    def find(v):
        while parent[v] != v:
            parent[v] = parent[parent[v]]
            v = parent[v]
        return v

    for a, b in zip(src, dst):
        ra, rb = find(int(a)), find(int(b))
        if ra != rb:
            parent[max(ra, rb)] = min(ra, rb)
    comps = {}
    seen = set(src.tolist()) | set(dst.tolist())
    for v in sorted(seen):
        comps.setdefault(find(v), []).append(v)
    expect = sorted(tuple(vs) for vs in comps.values())
    got = sorted(tuple(c) for c in resumed[0]["comps"])
    assert got == expect

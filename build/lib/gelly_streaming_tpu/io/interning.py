"""Vertex-id interning: arbitrary external ids -> dense [0, capacity) indices.

The reference keys operators by raw vertex ids through Flink's hash partitioner
(any Comparable key).  Dense device state instead requires a bounded id space,
and out-of-range ids silently corrupt XLA scatter/gather state — so the
interner is the framework's bounds guard (SURVEY.md §7 "interning" under the
central design problem).
"""

from __future__ import annotations

from typing import Dict, Hashable, List

import numpy as np


class VertexInterner:
    """Host-side incremental interner with reverse lookup.

    ``intern_ints`` vectorizes the common integer-id case; ``intern`` accepts
    any hashable ids (strings etc.).  Raises when capacity would be exceeded —
    loudly, because the device alternative is silent corruption.
    """

    def __init__(self, capacity: int):
        self.capacity = capacity
        self._fwd: Dict[Hashable, int] = {}
        self._rev: List[Hashable] = []

    def __len__(self) -> int:
        return len(self._rev)

    def intern(self, ids) -> np.ndarray:
        out = np.empty(len(ids), np.int32)
        fwd = self._fwd
        rev = self._rev
        for i, x in enumerate(ids):
            idx = fwd.get(x)
            if idx is None:
                idx = len(rev)
                if idx >= self.capacity:
                    raise ValueError(
                        f"vertex capacity {self.capacity} exceeded; raise "
                        f"StreamConfig.vertex_capacity"
                    )
                fwd[x] = idx
                rev.append(x)
            out[i] = idx
        return out

    def intern_ints(self, ids: np.ndarray) -> np.ndarray:
        """Vectorized interning for integer ids (dict only touched for new ids)."""
        ids = np.asarray(ids)
        uniq, first_pos = np.unique(ids, return_index=True)
        # Assign new dense ids in first-arrival order (stable across batchings).
        uniq = uniq[np.argsort(first_pos)]
        new = [u for u in uniq.tolist() if u not in self._fwd]
        for u in new:
            idx = len(self._rev)
            if idx >= self.capacity:
                raise ValueError(
                    f"vertex capacity {self.capacity} exceeded; raise "
                    f"StreamConfig.vertex_capacity"
                )
            self._fwd[u] = idx
            self._rev.append(u)
        try:
            lut_keys = np.fromiter(
                self._fwd.keys(), dtype=ids.dtype, count=len(self._fwd)
            )
        except (ValueError, TypeError):
            # mixed key types (e.g. strings interned earlier): generic path
            return self.intern(ids.tolist())
        lut_vals = np.fromiter(self._fwd.values(), dtype=np.int32, count=len(self._fwd))
        order = np.argsort(lut_keys)
        pos = np.searchsorted(lut_keys[order], ids)
        return lut_vals[order][pos].astype(np.int32)

    def lookup(self, idx: int) -> Hashable:
        return self._rev[idx]

    def lookup_many(self, idxs) -> List[Hashable]:
        return [self._rev[i] for i in idxs]


class IdentityInterner:
    """No-op interner for graphs whose ids are already dense ints < capacity
    (the test fixtures and generated benchmark graphs).  Still bounds-checks."""

    def __init__(self, capacity: int):
        self.capacity = capacity

    def intern_ints(self, ids: np.ndarray) -> np.ndarray:
        ids = np.asarray(ids)
        if len(ids) and (ids.min() < 0 or ids.max() >= self.capacity):
            raise ValueError(
                f"vertex id out of range [0, {self.capacity}); use VertexInterner"
            )
        return ids.astype(np.int32)

    def lookup(self, idx: int) -> int:
        return idx

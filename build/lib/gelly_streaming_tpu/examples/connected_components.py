"""Streaming Connected Components example
(reference: example/ConnectedComponentsExample.java:40-168).

Usage: connected_components [input-path [output-path [window-ms [--tree]]]]
Emits the running component sets (flattened DisjointSet) per merge window.
"""

from __future__ import annotations

from typing import List, Optional

from gelly_streaming_tpu.core.output import OutputStream
from gelly_streaming_tpu.examples._cli import emit, input_stream, parse_argv
from gelly_streaming_tpu.library.connected_components import (
    ConnectedComponents,
    ConnectedComponentsTree,
)

USAGE = "connected_components [input-path [output-path [window-ms [--tree]]]]"


def main(argv: Optional[List[str]] = None) -> None:
    args = parse_argv(argv, USAGE, 4)
    use_tree = "--tree" in args
    args = [a for a in args if a != "--tree"]
    window_ms = int(args[2]) if len(args) > 2 else 1000
    stream, output = input_stream(args)
    algo = (ConnectedComponentsTree if use_tree else ConnectedComponents)(window_ms)
    results = stream.aggregate(algo)
    # Flatten each window's summary into component rows (FlattenSet analog,
    # ConnectedComponentsExample.java:143-156).
    def records():
        for (ds,) in results:
            for root, members in sorted(ds.components().items()):
                yield (root, " ".join(str(v) for v in members))

    emit(OutputStream(records), output)


if __name__ == "__main__":
    main()

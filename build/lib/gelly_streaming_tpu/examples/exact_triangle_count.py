"""Insertion-only exact triangle count example
(reference: example/ExactTriangleCount.java:40-207).

Usage: exact_triangle_count [input-path [output-path]]
Emits continuous (vertexId, localCount) updates; key -1 carries the global count.
"""

from __future__ import annotations

from typing import List, Optional

from gelly_streaming_tpu.examples._cli import emit, input_stream, parse_argv
from gelly_streaming_tpu.library.triangles import ExactTriangleCount

USAGE = "exact_triangle_count [input-path [output-path]]"


def main(argv: Optional[List[str]] = None) -> None:
    args = parse_argv(argv, USAGE, 2)
    stream, output = input_stream(args)
    emit(ExactTriangleCount().run(stream), output)


if __name__ == "__main__":
    main()

"""Iterative (label-propagation) connected components example
(reference: example/IterativeConnectedComponents.java:45-229; the streaming
feedback loop is replaced by the on-device fixed point).

Usage: iterative_connected_components [input-path [output-path]]
Emits a continuous (vertex, componentId) stream.
"""

from __future__ import annotations

from typing import List, Optional

from gelly_streaming_tpu.examples._cli import emit, input_stream, parse_argv
from gelly_streaming_tpu.library.iterative_cc import IterativeConnectedComponents

USAGE = "iterative_connected_components [input-path [output-path]]"


def main(argv: Optional[List[str]] = None) -> None:
    args = parse_argv(argv, USAGE, 2)
    stream, output = input_stream(args)
    emit(IterativeConnectedComponents().run(stream), output)


if __name__ == "__main__":
    main()

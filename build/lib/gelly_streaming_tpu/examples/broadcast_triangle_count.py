"""Broadcast sampling triangle-count estimate example
(reference: example/BroadcastTriangleCount.java:38-270).

Usage: broadcast_triangle_count [input-path [output-path [samples]]]
Emits the running triangle-count estimate after each micro-batch.
"""

from __future__ import annotations

from typing import List, Optional

from gelly_streaming_tpu.examples._cli import emit, input_stream, parse_argv
from gelly_streaming_tpu.library.sampled_triangles import BroadcastTriangleCount

USAGE = "broadcast_triangle_count [input-path [output-path [samples]]]"


def main(argv: Optional[List[str]] = None) -> None:
    args = parse_argv(argv, USAGE, 3)
    samples = int(args[2]) if len(args) > 2 else 1000
    stream, output = input_stream(args)
    emit(BroadcastTriangleCount(num_samplers=samples).run(stream), output)


if __name__ == "__main__":
    main()

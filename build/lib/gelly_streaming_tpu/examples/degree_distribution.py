"""Fully-dynamic degree distribution example
(reference: example/DegreeDistribution.java:43-193).

Usage: degree_distribution [input-path [output-path]]
Input lines are ``src dst +`` / ``src dst -`` (edge additions/deletions);
emits continuous (degree, count) histogram updates.
"""

from __future__ import annotations

from typing import List, Optional

from gelly_streaming_tpu.examples._cli import DEFAULT_CFG, emit, parse_argv
from gelly_streaming_tpu.io.sources import file_stream, generated_stream
from gelly_streaming_tpu.library.degree_distribution import DegreeDistribution

USAGE = "degree_distribution [input-path [output-path]]"


def main(argv: Optional[List[str]] = None) -> None:
    args = parse_argv(argv, USAGE, 2)
    if args:
        stream, _ = file_stream(args[0], DEFAULT_CFG, batch_size=64)
    else:
        stream = generated_stream(DEFAULT_CFG, 1000, num_vertices=100)
    output = args[1] if len(args) > 1 else None
    emit(DegreeDistribution().run(stream), output)


if __name__ == "__main__":
    main()

"""Measurement programs: degree / bipartiteness / triangle throughput+latency.

The reference's pom.xml declares three measurement jars —
``example.degrees.DegreeMeasurement``, ``example.bipartiteness.
BipartiteMeasurement``, ``example.triangles.TriangleMeasurements``
(pom.xml:144-188) — whose classes do not exist in its source tree (an
out-of-tree benchmarking branch, SURVEY.md §6).  This module supplies working
equivalents: each subcommand drives the framework's real ingest path (wire
pack -> prefetched transfer -> jitted fold, as in bench.py) for one workload
and prints ONE JSON line of metrics.

  python -m gelly_streaming_tpu.examples.measurements degrees       [options]
  python -m gelly_streaming_tpu.examples.measurements bipartiteness [options]
  python -m gelly_streaming_tpu.examples.measurements triangles    [options]

Options: --edges N --vertices C --batch B --seed S; triangles also takes
--windows W --pane-vertices K (panes are K-vertex random graphs counted with
the MXU kernel; reports p50/p95 per-window latency).
"""

from __future__ import annotations

import argparse
import json
import sys
from typing import List, Optional

import numpy as np


def _stream_fold(num_edges, capacity, batch, seed, make_fold, init_state):
    """Synthetic edge stream through the shared wire-ingest harness."""
    from gelly_streaming_tpu.utils.ingest_bench import wire_stream_fold

    if num_edges < 2:
        raise SystemExit("--edges must be at least 2")
    rng = np.random.default_rng(seed)
    src = rng.integers(0, capacity, num_edges).astype(np.int32)
    dst = rng.integers(0, capacity, num_edges).astype(np.int32)
    return wire_stream_fold(src, dst, capacity, batch, make_fold, init_state)


def measure_degrees(args) -> dict:
    """Continuous degree stream fold (getDegrees hot path,
    SimpleEdgeStream.java:461-478 as a dense segment add)."""
    import jax.numpy as jnp

    from gelly_streaming_tpu.io import wire
    from gelly_streaming_tpu.ops import segments

    def make_fold(batch, width):
        def fold(counts, buf):
            s, d = wire.unpack_edges(buf, batch, width)
            v = jnp.concatenate([s, d])
            return counts + segments.segment_sum(
                jnp.ones_like(v), v, counts.shape[0], None
            )

        return fold

    eps, folded, counts = _stream_fold(
        args.edges,
        args.vertices,
        args.batch,
        args.seed,
        make_fold,
        lambda: jnp.zeros((args.vertices,), jnp.int32),
    )
    total = int(np.asarray(counts).sum())
    return {
        "workload": "degrees",
        "edges_per_sec": round(eps, 1),
        "edges_folded": folded,
        "degree_total": total,
    }


def measure_bipartiteness(args) -> dict:
    """Streaming 2-coloring fold (BipartitenessCheck hot path as the
    doubled-vertex parity union-find, ops/unionfind.py)."""
    import jax.numpy as jnp

    from gelly_streaming_tpu.io import wire
    from gelly_streaming_tpu.ops import unionfind as uf

    def make_fold(batch, width):
        def fold(state, buf):
            parent2, seen = state
            s, d = wire.unpack_edges(buf, batch, width)
            parent2 = uf.parity_union_edges(parent2, s, d, None)
            seen = seen.at[s].max(True).at[d].max(True)
            return parent2, seen

        return fold

    eps, folded, (parent2, seen) = _stream_fold(
        args.edges,
        args.vertices,
        args.batch,
        args.seed,
        make_fold,
        lambda: (
            uf.init_parity_parent(args.vertices),
            jnp.zeros((args.vertices,), bool),
        ),
    )
    ok = bool(uf.is_bipartite(parent2, seen))
    return {
        "workload": "bipartiteness",
        "edges_per_sec": round(eps, 1),
        "edges_folded": folded,
        "bipartite": ok,
    }


def measure_triangles(args) -> dict:
    """Per-window exact triangle count latency (WindowTriangles hot path via
    the Pallas MXU kernel, ops/pallas_triangles.py)."""
    from gelly_streaming_tpu.library.triangles import _pane_triangle_count
    from gelly_streaming_tpu.utils.metrics import WindowLatencyRecorder

    rng = np.random.default_rng(args.seed)
    rec = WindowLatencyRecorder()
    k = args.pane_vertices
    per_pane = max(1, args.edges // max(1, args.windows))
    # unmetered warmup pane: the first call compiles the kernel (hundreds of
    # ms), which would otherwise dominate the latency percentiles
    _pane_triangle_count(
        rng.integers(0, k, per_pane).astype(np.int32),
        rng.integers(0, k, per_pane).astype(np.int32),
    )
    total = 0
    for _ in range(args.windows):
        src = rng.integers(0, k, per_pane).astype(np.int32)
        dst = rng.integers(0, k, per_pane).astype(np.int32)
        rec.window_closed()
        total += _pane_triangle_count(src, dst)
        rec.result_emitted()
    return {
        "workload": "triangles",
        "windows": args.windows,
        "edges_per_window": per_pane,
        "pane_vertices": k,
        "triangles_total": int(total),
        "p50_window_ms": round(rec.percentile(50), 2),
        "p95_window_ms": round(rec.percentile(95), 2),
    }


def main(argv: Optional[List[str]] = None) -> None:
    p = argparse.ArgumentParser(prog="measurements", description=__doc__)
    sub = p.add_subparsers(dest="workload", required=True)
    for name in ("degrees", "bipartiteness"):
        sp = sub.add_parser(name)
        sp.add_argument("--edges", type=int, default=1 << 20)
        sp.add_argument("--vertices", type=int, default=1 << 17)
        sp.add_argument("--batch", type=int, default=1 << 16)
        sp.add_argument("--seed", type=int, default=0)
    sp = sub.add_parser("triangles")
    sp.add_argument("--edges", type=int, default=1 << 17)
    sp.add_argument("--seed", type=int, default=0)
    sp.add_argument("--windows", type=int, default=8)
    sp.add_argument("--pane-vertices", type=int, default=1024)
    args = p.parse_args(argv)
    fn = {
        "degrees": measure_degrees,
        "bipartiteness": measure_bipartiteness,
        "triangles": measure_triangles,
    }[args.workload]
    print(json.dumps(fn(args)))


if __name__ == "__main__":
    main(sys.argv[1:])

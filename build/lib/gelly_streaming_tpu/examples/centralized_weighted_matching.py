"""Greedy streaming weighted matching example
(reference: example/CentralizedWeightedMatching.java:36-113; reads a weighted
edge list — the reference hardcodes movielens_10k_sorted.txt — and prints
ADD/REMOVE MatchingEvents plus the net runtime, :62-64).

Usage: centralized_weighted_matching [input-path [output-path]]
"""

from __future__ import annotations

import time
from typing import List, Optional

import numpy as np

from gelly_streaming_tpu.core.stream import EdgeStream
from gelly_streaming_tpu.core.types import EdgeBatch
from gelly_streaming_tpu.examples._cli import DEFAULT_CFG, emit, parse_argv
from gelly_streaming_tpu.io.sources import file_stream
from gelly_streaming_tpu.library.matching import CentralizedWeightedMatching

USAGE = "centralized_weighted_matching [input-path [output-path]]"


def _generated_weighted(cfg, num_edges=1000, num_vertices=100, seed=0):
    rng = np.random.default_rng(seed)
    src = rng.integers(0, num_vertices, num_edges).astype(np.int32)
    dst = rng.integers(0, num_vertices, num_edges).astype(np.int32)
    w = rng.integers(1, 100, num_edges).astype(np.float32)

    def factory():
        bs = cfg.batch_size
        for i in range(0, num_edges, bs):
            j = min(i + bs, num_edges)
            yield EdgeBatch.from_arrays(src[i:j], dst[i:j], val=w[i:j], pad_to=bs)

    return EdgeStream.from_batches(factory, cfg)


def main(argv: Optional[List[str]] = None) -> None:
    args = parse_argv(argv, USAGE, 2)
    if args:
        stream, _ = file_stream(args[0], DEFAULT_CFG)
    else:
        stream = _generated_weighted(DEFAULT_CFG)
    output = args[1] if len(args) > 1 else None
    t0 = time.perf_counter()
    emit(CentralizedWeightedMatching().run(stream), output)
    print(f"Runtime: {int((time.perf_counter() - t0) * 1000)}")


if __name__ == "__main__":
    main()

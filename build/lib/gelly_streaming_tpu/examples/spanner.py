"""k-Spanner example (reference: example/SpannerExample.java:40-165).

Usage: spanner [input-path [output-path [window-ms [k]]]]
Emits the spanner's edge set per merge window (flatten-and-print analog,
SpannerExample.java:61-67).
"""

from __future__ import annotations

from typing import List, Optional

from gelly_streaming_tpu.core.output import OutputStream
from gelly_streaming_tpu.examples._cli import emit, input_stream, parse_argv
from gelly_streaming_tpu.library.spanner import Spanner

USAGE = "spanner [input-path [output-path [window-ms [k]]]]"


def main(argv: Optional[List[str]] = None) -> None:
    args = parse_argv(argv, USAGE, 4)
    window_ms = int(args[2]) if len(args) > 2 else 1000
    k = int(args[3]) if len(args) > 3 else 3
    stream, output = input_stream(args)
    results = stream.aggregate(Spanner(window_ms, k))

    def records():
        for (g,) in results:
            for u, v in sorted(g.edges()):
                yield (u, v)

    emit(OutputStream(records), output)


if __name__ == "__main__":
    main()

"""Bipartiteness check example
(reference: example/BipartitenessCheckExample.java:38-124, window 500ms).

Usage: bipartiteness_check [input-path [output-path [window-ms]]]
"""

from __future__ import annotations

from typing import List, Optional

from gelly_streaming_tpu.examples._cli import emit, input_stream, parse_argv
from gelly_streaming_tpu.library.bipartiteness import BipartitenessCheck

USAGE = "bipartiteness_check [input-path [output-path [window-ms]]]"


def main(argv: Optional[List[str]] = None) -> None:
    args = parse_argv(argv, USAGE, 3)
    window_ms = int(args[2]) if len(args) > 2 else 500
    stream, output = input_stream(args)
    emit(stream.aggregate(BipartitenessCheck(window_ms)), output)


if __name__ == "__main__":
    main()

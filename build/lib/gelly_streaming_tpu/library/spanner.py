"""Continuous k-spanner (library/Spanner.java:40-118).

Reference semantics: per edge, run a k-bounded BFS between the endpoints on the
current spanner; admit the edge only if the distance exceeds k (:71-77).  The
combine re-inserts the smaller spanner's edges into the larger under the same
test (:92-116).  Admission decisions are inherently sequential (each depends on
the previous), so the fold is a ``lax.scan`` over the batch, with the k-step
dense frontier-expansion BFS (summaries/adjacency.py) as the inner kernel —
the per-edge decision is a fixed-depth array program instead of a queue walk.
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp

from gelly_streaming_tpu.core.aggregation import SummaryBulkAggregation
from gelly_streaming_tpu.core.config import StreamConfig
from gelly_streaming_tpu.summaries import adjacency
from gelly_streaming_tpu.summaries.adjacency import AdjacencyListGraph


class SpannerState(NamedTuple):
    nbrs: jax.Array  # int32[C, D]
    deg: jax.Array  # int32[C]


class Spanner(SummaryBulkAggregation):
    """aggregate(Spanner(window_ms, k)) -> stream of AdjacencyListGraph views."""

    def __init__(self, window_ms: int, k: int):
        super().__init__(window_ms)
        self.k = k

    def initial_state(self, cfg: StreamConfig) -> SpannerState:
        nbrs, deg = adjacency.init_table(cfg.vertex_capacity, cfg.max_degree)
        return SpannerState(nbrs, deg)

    def update(self, state: SpannerState, src, dst, val, mask) -> SpannerState:
        k = self.k

        def step(carry, inp):
            nbrs, deg = carry
            u, v, ok = inp
            within_k = adjacency.bounded_bfs(nbrs, u, v, k)
            nbrs, deg = adjacency.add_undirected_edge(
                nbrs, deg, u, v, enabled=ok & ~within_k
            )
            return (nbrs, deg), None

        (nbrs, deg), _ = jax.lax.scan(
            step, (state.nbrs, state.deg), (src, dst, mask)
        )
        return SpannerState(nbrs, deg)

    def combine(self, a: SpannerState, b: SpannerState) -> SpannerState:
        """Re-insert the smaller spanner's edges into the larger
        (CombineSpanners, Spanner.java:92-116).  Edges of the smaller are
        enumerated as canonical (v, nbr) slot pairs of its table."""
        k = self.k
        size_a = jnp.sum((a.deg > 0).astype(jnp.int32))
        size_b = jnp.sum((b.deg > 0).astype(jnp.int32))

        def merge(big: SpannerState, small: SpannerState) -> SpannerState:
            capacity, max_degree = small.nbrs.shape
            vs = jnp.repeat(jnp.arange(capacity, dtype=jnp.int32), max_degree)
            ns = small.nbrs.reshape(-1)
            slot_ok = (ns >= 0) & (vs < ns)  # canonical: insert each edge once

            def step(carry, inp):
                nbrs, deg = carry
                u, v, ok = inp
                v = jnp.maximum(v, 0)  # -1 empty slots (ok is False there)
                within_k = adjacency.bounded_bfs(nbrs, u, v, k)
                nbrs, deg = adjacency.add_undirected_edge(
                    nbrs, deg, u, v, enabled=ok & ~within_k
                )
                return (nbrs, deg), None

            (nbrs, deg), _ = jax.lax.scan(
                step, (big.nbrs, big.deg), (vs, ns, slot_ok)
            )
            return SpannerState(nbrs, deg)

        return jax.lax.cond(
            size_a >= size_b, lambda: merge(a, b), lambda: merge(b, a)
        )

    def transform(self, state: SpannerState) -> AdjacencyListGraph:
        return AdjacencyListGraph.from_state(state.nbrs, state.deg)

"""Batched array union-find: the framework's hot kernel.

The reference's streaming Connected Components folds every edge through a
pointer-chasing, recursively path-compressing ``DisjointSet``
(summaries/DisjointSet.java:66-118) — inherently sequential, one edge at a time.
The TPU-native replacement operates on a dense ``parent: int32[C]`` array and
processes a whole edge micro-batch with scatter-min *hooking* plus
pointer-doubling *compression* (Shiloach–Vishkin style), converging to the same
fixed point: ``parent[v]`` is the minimum vertex id in v's component.

All functions are pure and jittable; state threads through functionally.
"""

from __future__ import annotations

from typing import Optional, Tuple

import jax
import jax.numpy as jnp


def init_parent(capacity: int) -> jax.Array:
    """Every vertex its own singleton root."""
    return jnp.arange(capacity, dtype=jnp.int32)


def compress(parent: jax.Array) -> jax.Array:
    """Full pointer-doubling until every entry points at its root.

    Replaces the recursive find+path-compression of DisjointSet.java:66-81 with a
    log-depth whole-array iteration.
    """

    def cond(state):
        p, changed = state
        return changed

    def body(state):
        p, _ = state
        p2 = p[p]
        return p2, jnp.any(p2 != p)

    p, _ = jax.lax.while_loop(cond, body, (parent, jnp.array(True)))
    return p


def find_roots(parent: jax.Array, vertices: jax.Array) -> jax.Array:
    """Chase parent pointers for a vector of vertices (no mutation)."""

    def cond(r):
        return jnp.any(parent[r] != r)

    def body(r):
        return parent[r]

    return jax.lax.while_loop(cond, body, vertices)


def union_edges(
    parent: jax.Array,
    src: jax.Array,
    dst: jax.Array,
    mask: Optional[jax.Array] = None,
) -> jax.Array:
    """Merge the components of every valid (src, dst) edge in the batch.

    Equivalent fixed point to folding each edge through DisjointSet.union
    (summaries/DisjointSet.java:92-118), but order-free and batched:

      repeat until all edges have equal endpoint roots:
        hook:     parent[max(root_s, root_d)] <- min over edges (scatter-min)
        compress: full pointer doubling

    Masked rows are turned into self-loops and cannot affect state.
    """
    if mask is not None:
        src = jnp.where(mask, src, 0)
        dst = jnp.where(mask, dst, 0)

    def cond(p):
        return jnp.any(p[src] != p[dst])

    def body(p):
        rs = p[src]
        rd = p[dst]
        lo = jnp.minimum(rs, rd)
        hi = jnp.maximum(rs, rd)
        p = p.at[hi].min(lo)
        return compress(p)

    return jax.lax.while_loop(cond, body, compress(parent))


def merge_parents(parent_a: jax.Array, parent_b: jax.Array) -> jax.Array:
    """Combine two union-find summaries over the same vertex space.

    The reference merges two DisjointSets by re-unioning every (elem -> parent)
    entry of the smaller into the larger (DisjointSet.java:127-131).  Array-form:
    treat b's pointers as edges (v, parent_b[v]) and apply them to a.  Since both
    arrays are total over [0, C), this is one batched union over C edges.
    """
    v = jnp.arange(parent_a.shape[0], dtype=jnp.int32)
    return union_edges(parent_a, v, parent_b, mask=None)


def union_edges_with_seen(
    parent: jax.Array,
    seen: jax.Array,
    src: jax.Array,
    dst: jax.Array,
    mask: Optional[jax.Array] = None,
) -> Tuple[jax.Array, jax.Array]:
    """union_edges plus tracking of which vertices have appeared.

    ``seen`` distinguishes real components from untouched identity entries when
    enumerating components (DisjointSet's map only contains added elements,
    DisjointSet.java:40-46; a dense array must track membership explicitly).
    """
    parent = union_edges(parent, src, dst, mask)
    if mask is None:
        mask = jnp.ones(src.shape, bool)
    seen = seen.at[jnp.where(mask, src, 0)].max(mask)
    seen = seen.at[jnp.where(mask, dst, 0)].max(mask)
    return parent, seen


# ---------------------------------------------------------------------------
# Signed (parity) union-find — the bipartiteness summary.
# ---------------------------------------------------------------------------
#
# The reference's Candidates summary tracks per-vertex signs inside per-component
# maps and fails on sign conflicts (summaries/Candidates.java:61-139).  The
# array-native re-derivation uses the classic doubled-vertex construction: each
# vertex v becomes two nodes (2v = "v on side A", 2v+1 = "v on side B"); an edge
# (u, w) asserts u and w are on opposite sides, i.e. union(2u, 2w+1) and
# union(2u+1, 2w).  The graph is non-bipartite iff some vertex's two sides end up
# in the same component.  Same fixed point as Candidates' merge-with-sign-flip,
# with no nested maps.


def init_parity_parent(capacity: int) -> jax.Array:
    return init_parent(2 * capacity)


def parity_union_edges(
    parent2: jax.Array,
    src: jax.Array,
    dst: jax.Array,
    mask: Optional[jax.Array] = None,
) -> jax.Array:
    """Apply opposite-side constraints for a batch of edges to the doubled space."""
    if mask is not None:
        # masked rows become (0, 0) self-unions
        a1 = jnp.where(mask, 2 * src, 0)
        b1 = jnp.where(mask, 2 * dst + 1, 0)
        a2 = jnp.where(mask, 2 * src + 1, 0)
        b2 = jnp.where(mask, 2 * dst, 0)
    else:
        a1, b1, a2, b2 = 2 * src, 2 * dst + 1, 2 * src + 1, 2 * dst
    s = jnp.concatenate([a1, a2])
    d = jnp.concatenate([b1, b2])
    return union_edges(parent2, s, d)


def parity_conflicts(parent2: jax.Array, seen: jax.Array) -> jax.Array:
    """True where a seen vertex's two sides collapsed (odd cycle through v)."""
    c = parent2.shape[0] // 2
    even = parent2[2 * jnp.arange(c)]
    odd = parent2[2 * jnp.arange(c) + 1]
    return seen & (even == odd)


def is_bipartite(parent2: jax.Array, seen: jax.Array) -> jax.Array:
    return ~jnp.any(parity_conflicts(parent2, seen))

"""Record output streams and sinks.

The reference's property streams are Flink ``DataStream``s written with
``writeAsCsv`` or collected in test sinks (e.g. TestGetDegrees.java:54-56,
ConnectedComponentsTest.java:84-94).  Here a terminal op yields per-batch record
blocks (dict of equal-length host arrays + validity mask); ``OutputStream``
wraps that iterator with collect/CSV sinks using the same rendering the golden
files assert (Flink Tuple CSV: ``1,2,12``; NullValue -> ``(null)``; nested
tuples -> ``(12,13)``).
"""

from __future__ import annotations

from typing import Callable, Iterable, Iterator, List, Optional, Tuple

import numpy as np


class NullValue:
    """Singleton mirroring Flink's NullValue; renders as ``(null)`` in CSV."""

    _inst = None

    def __new__(cls):
        if cls._inst is None:
            cls._inst = super().__new__(cls)
        return cls._inst

    def __repr__(self):
        return "(null)"


NULL = NullValue()


def _render(x) -> str:
    if isinstance(x, NullValue):
        return "(null)"
    if isinstance(x, tuple):
        return "(" + ",".join(_render(v) for v in x) + ")"
    if isinstance(x, (bool, np.bool_)):
        return "true" if x else "false"
    if isinstance(x, (float, np.floating)):
        return repr(float(x))
    if isinstance(x, (int, np.integer)):
        return str(int(x))
    return str(x)


class OutputStream:
    """A continuous stream of records produced by a terminal operation.

    ``records_fn`` is a zero-arg callable returning an iterator of host tuples
    (so the stream can be re-run, mirroring a dataflow's lazy execution).
    """

    def __init__(self, records_fn: Callable[[], Iterator[tuple]]):
        self._records_fn = records_fn

    def __iter__(self) -> Iterator[tuple]:
        return self._records_fn()

    def collect(self) -> List[tuple]:
        return list(self._records_fn())

    def collect_last(self) -> Optional[tuple]:
        last = None
        for r in self._records_fn():
            last = r
        return last

    def lines(self) -> List[str]:
        """CSV lines in the reference's writeAsCsv rendering."""
        return [",".join(_render(f) for f in rec) for rec in self._records_fn()]

    def write_csv(self, path: str) -> None:
        with open(path, "w") as f:
            for line in self.lines():
                f.write(line + "\n")

    def print(self) -> None:
        for rec in self._records_fn():
            print(",".join(_render(f) for f in rec))

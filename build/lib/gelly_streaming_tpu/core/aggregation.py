"""The aggregation runtime: windowed partial-fold + combine + running merge.

Reference: SummaryAggregation.java (descriptor: updateFun :31, combineFun :36,
transform :41, initialValue :43, transientState :48; the singleton Merger
final-combiner :93-119 with ListCheckpointed state :127-135) and its two
execution strategies SummaryBulkAggregation.java:68-90 (per-partition windowed
fold -> flat all-window combine) and SummaryTreeReduce.java:95-123 (log-depth
pairwise combine tree).

TPU-native form: a "partition" is a shard of the window pane; the per-partition
fold is a batched state-update kernel; the flat combine is a left fold over
partials; the tree combine is pairwise rounds (halving, mirroring enhance()'s
``partition/2`` re-keying).  The running summary (Merger state) is a pytree of
arrays — checkpointable by construction, closing the reference's gap where most
operator state is not checkpointed (SURVEY.md §5.3-4).
"""

from __future__ import annotations

import os
from typing import Any, Callable, Iterator, Optional

import jax
import jax.numpy as jnp
import numpy as np

from gelly_streaming_tpu.core.config import StreamConfig
from gelly_streaming_tpu.core.output import OutputStream
from gelly_streaming_tpu.core.windows import WindowPane, assign_tumbling_windows


class SummaryAggregation:
    """Abstract aggregation descriptor (SummaryAggregation.java:22-48).

    Subclasses define:
      initial_state(cfg) -> S          (initialValue :43; pytree of arrays)
      update(state, src, dst, val, mask) -> S   (updateFun :31 — folds an edge
                                        micro-batch into the partial state)
      combine(a, b) -> S               (combineFun :36 — merge partials)
      transform(state) -> T            (transform :41 — S to emitted record)
    ``transient_state`` resets the running summary after each emission
    (SummaryAggregation.java:113-115).
    """

    transient_state: bool = False

    def __init__(self, window_ms: Optional[int] = None):
        self.window_ms = window_ms

    # -- descriptor hooks -----------------------------------------------------

    def initial_state(self, cfg: StreamConfig):
        raise NotImplementedError

    def update(self, state, src, dst, val, mask):
        raise NotImplementedError

    def combine(self, a, b):
        raise NotImplementedError

    def transform(self, state):
        return state

    # -- execution ------------------------------------------------------------

    def _num_partitions(self, cfg: StreamConfig) -> int:
        return cfg.num_shards

    def _fold_partials(self, items, combine2):
        """Combine-strategy hook over opaque items: flat left fold
        (timeWindowAll.reduce analog, SummaryBulkAggregation.java:81-83).
        Overridden by the tree strategy.  Shared by the simulated runtime and
        the mesh runner so the strategies cannot diverge."""
        acc = items[0]
        for it in items[1:]:
            acc = combine2(acc, it)
        return acc

    def _combine_partials(self, partials):
        return self._fold_partials(partials, self._combine_j)

    @property
    def _update_j(self):
        if not hasattr(self, "_update_cache"):
            self._update_cache = jax.jit(self.update)
        return self._update_cache

    @property
    def _combine_j(self):
        if not hasattr(self, "_combine_cache"):
            self._combine_cache = jax.jit(self.combine)
        return self._combine_cache

    def run(
        self,
        stream,
        checkpoint_path: Optional[str] = None,
        restore: bool = True,
    ) -> OutputStream:
        """Execute over an EdgeStream (entered via GraphStream.aggregate,
        GraphStream.java:139-140 / SimpleEdgeStream.java:100-102).

        With ``checkpoint_path``, the running summary is snapshot after every
        window close and restored on start — the Merger's ListCheckpointed
        behavior (SummaryAggregation.java:127-135), generalized to the whole
        summary pytree (closing the reference's unsaved-state gap)."""
        cfg = stream.cfg
        window_ms = self.window_ms or cfg.window_ms
        n_parts = self._num_partitions(cfg)

        def records() -> Iterator[tuple]:
            running = None
            if checkpoint_path and restore:
                from gelly_streaming_tpu.utils.checkpoint import (
                    checkpoint_exists,
                    load_state,
                )

                if checkpoint_exists(checkpoint_path):
                    running = load_state(checkpoint_path, self.initial_state(cfg))
            for pane in assign_tumbling_windows(stream.batches(), window_ms):
                partials = []
                for part in range(n_parts):
                    # Round-robin partitioning of the pane stands in for the
                    # reference's source-subtask tagging (PartitionMapper,
                    # SummaryBulkAggregation.java:93-106).
                    sel = np.arange(len(pane.src)) % n_parts == part
                    if not sel.any():
                        continue
                    # Pad to the next power of two so varying pane sizes hit a
                    # small, bounded set of compiled kernel shapes.
                    n = int(sel.sum())
                    padded = max(1, 1 << (n - 1).bit_length())
                    mask = np.zeros((padded,), bool)
                    mask[:n] = True

                    def pad(a, fill=0):
                        out = np.full((padded,) + a.shape[1:], fill, a.dtype)
                        out[:n] = a[sel]
                        return out

                    state = self.initial_state(cfg)
                    state = self._update_j(
                        state,
                        jnp.asarray(pad(pane.src), jnp.int32),
                        jnp.asarray(pad(pane.dst), jnp.int32),
                        None
                        if pane.val is None
                        else jax.tree.map(lambda a: jnp.asarray(pad(a)), pane.val),
                        jnp.asarray(mask),
                    )
                    partials.append(state)
                if not partials:
                    continue
                pane_summary = self._combine_partials(partials)
                # Merger: non-blocking running merge, one emission per window
                # close (SummaryAggregation.java:107-119).
                if running is None or self.transient_state:
                    running = pane_summary
                else:
                    running = self._combine_j(running, pane_summary)
                out = self.transform(running)
                if checkpoint_path:
                    from gelly_streaming_tpu.utils.checkpoint import save_state

                    save_state(checkpoint_path, running)
                yield out if isinstance(out, tuple) else (out,)
                if self.transient_state:
                    running = None

        return OutputStream(records)


class SummaryBulkAggregation(SummaryAggregation):
    """Flat combine strategy (SummaryBulkAggregation.java:51-90)."""


class SummaryTreeAggregation(SummaryAggregation):
    """Log-depth pairwise combine (SummaryTreeReduce.java:47-123): partials are
    merged in halving rounds (key = partition/2) instead of one flat fold —
    same fixed point for associative combines, fewer sequential merge steps."""

    def _fold_partials(self, items, combine2):
        level = list(items)
        while len(level) > 1:
            nxt = []
            for i in range(0, len(level) - 1, 2):
                nxt.append(combine2(level[i], level[i + 1]))
            if len(level) % 2:
                nxt.append(level[-1])
            level = nxt
        return level[0]


class MeshAggregationRunner:
    """Execute a SummaryAggregation's window fold+combine over a device mesh.

    The single-device ``run`` above *simulates* partitions sequentially (the
    MiniCluster shape); this runner is the real multi-chip data plane: each
    window pane is bucketed round-robin across shards on the host, and ONE
    jitted ``shard_map`` step does the per-shard fold (updateFun over the
    shard's bucket), an ``all_gather`` of the partial summaries over the mesh
    axis (riding ICI), and the combine fold — replacing the reference's
    keyBy -> per-partition windowed fold -> timeWindowAll network pipeline
    (SummaryBulkAggregation.java:76-83) with collectives.

    The combine strategy (flat vs tree) comes from the descriptor class
    itself (``_fold_partials``), exactly as in the simulated runtime; with
    one all_gather the communication is identical either way (ICI collectives
    are already ring/tree structured), only the local combine order changes.
    Shards whose bucket is empty are excluded from the combine by masking —
    matching the simulated runtime, which skips empty partitions, so
    descriptors whose initial state is not a combine identity still agree.

    The running cross-window merge stays on device, replicated over the mesh.
    """

    def __init__(self, agg: SummaryAggregation, mesh=None):
        from gelly_streaming_tpu.parallel import mesh as mesh_mod

        self.agg = agg
        self.mesh = mesh if mesh is not None else mesh_mod.make_mesh()
        self._axis = mesh_mod.SHARD_AXIS
        self._step_cache = {}

    @property
    def num_shards(self) -> int:
        return self.mesh.devices.size

    def _pane_step(self, cfg: StreamConfig, cap: int, has_val: bool):
        """Compiled sharded fold+combine for panes bucketed at capacity cap."""
        key = (cfg, cap, has_val)
        if key in self._step_cache:
            return self._step_cache[key]
        from jax.sharding import PartitionSpec as P

        from gelly_streaming_tpu.parallel.mesh import shard_map

        agg, axis, n = self.agg, self._axis, self.num_shards

        def masked_combine(a, b):
            """Combine (state, valid) pairs, ignoring empty-shard partials."""
            sa, va = a
            sb, vb = b
            merged = agg.combine(sa, sb)
            both = va & vb
            state = jax.tree.map(
                lambda m, x, y: jnp.where(both, m, jnp.where(va, x, y)),
                merged,
                sa,
                sb,
            )
            return state, va | vb

        def step(src, dst, val, mask):
            # [1, cap] per shard inside shard_map: fold this shard's bucket
            state = agg.initial_state(cfg)
            state = agg.update(
                state,
                src[0],
                dst[0],
                None if val is None else jax.tree.map(lambda a: a[0], val),
                mask[0],
            )
            gathered = jax.tree.map(
                lambda a: jax.lax.all_gather(a, axis), state
            )
            has_data = jax.lax.all_gather(jnp.any(mask[0]), axis)
            parts = [
                (jax.tree.map(lambda g: g[i], gathered), has_data[i])
                for i in range(n)
            ]
            acc, _ = agg._fold_partials(parts, masked_combine)
            return acc

        spec = P(self._axis)
        val_spec = spec if has_val else None
        fn = jax.jit(
            shard_map(
                step,
                mesh=self.mesh,
                in_specs=(spec, spec, val_spec, spec),
                out_specs=P(),
            )
        )
        self._step_cache[key] = fn
        return fn

    def _bucket_pane(self, pane: WindowPane):
        """Round-robin the pane's edges into [n_shards, cap] host arrays."""
        n = self.num_shards
        total = len(pane.src)
        per = -(-max(total, 1) // n)  # ceil, >= 1
        cap = max(1, 1 << (per - 1).bit_length())  # bounded set of shapes
        src = np.zeros((n, cap), np.int32)
        dst = np.zeros((n, cap), np.int32)
        mask = np.zeros((n, cap), bool)
        val = None
        if pane.val is not None:
            val = jax.tree.map(
                lambda a: np.zeros((n, cap) + a.shape[1:], a.dtype), pane.val
            )
        for shard in range(n):
            idx = np.arange(shard, total, n)
            k = len(idx)
            src[shard, :k] = pane.src[idx]
            dst[shard, :k] = pane.dst[idx]
            mask[shard, :k] = True
            if val is not None:

                def fill(buf, a):
                    buf[shard, :k] = a[idx]
                    return buf

                val = jax.tree.map(fill, val, pane.val)
        return src, dst, val, mask

    def run(self, stream, window_ms: Optional[int] = None) -> OutputStream:
        """(transform(running_summary),) per closed window, like run()."""
        cfg = stream.cfg
        window_ms = window_ms or self.agg.window_ms or cfg.window_ms
        agg = self.agg

        def records() -> Iterator[tuple]:
            running = None
            for pane in assign_tumbling_windows(stream.batches(), window_ms):
                if len(pane.src) == 0:
                    continue
                src, dst, val, mask = self._bucket_pane(pane)
                step = self._pane_step(cfg, src.shape[1], val is not None)
                pane_summary = step(
                    jnp.asarray(src),
                    jnp.asarray(dst),
                    None if val is None else jax.tree.map(jnp.asarray, val),
                    jnp.asarray(mask),
                )
                if running is None or agg.transient_state:
                    running = pane_summary
                else:
                    running = agg._combine_j(running, pane_summary)
                out = agg.transform(running)
                yield out if isinstance(out, tuple) else (out,)
                if agg.transient_state:
                    running = None

        return OutputStream(records)



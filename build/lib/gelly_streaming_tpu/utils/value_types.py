"""Wire value types (reference: util/*.java Tuple subclasses).

These are plain host-side records; on device the same information travels as
columns of batch arrays (the tuple-of-arrays dual of Flink's array-of-tuples).
"""

from __future__ import annotations

import dataclasses
from typing import Tuple


@dataclasses.dataclass(frozen=True)
class SignedVertex:
    """(vertexId, sign) — util/SignedVertex.java:23-41."""

    vertex: int
    sign: bool

    def as_tuple(self) -> Tuple:
        return (self.vertex, self.sign)

    def __str__(self):
        return f"({self.vertex},{'true' if self.sign else 'false'})"


@dataclasses.dataclass(frozen=True)
class MatchingEvent:
    """(ADD/REMOVE, edge) — util/MatchingEvent.java:24-42."""

    type: str  # "ADD" | "REMOVE"
    src: int
    dst: int
    weight: float

    def as_tuple(self) -> Tuple:
        return (self.type, self.src, self.dst, self.weight)

    def __str__(self):
        return f"({self.type},{self.src},{self.dst},{self.weight})"


@dataclasses.dataclass(frozen=True)
class SampledEdge:
    """(subtask, instance, edge, edgeCount, resample) — util/SampledEdge.java:25."""

    subtask: int
    instance: int
    src: int
    dst: int
    edge_count: int
    resample: bool

    def as_tuple(self) -> Tuple:
        return (
            self.subtask,
            self.instance,
            self.src,
            self.dst,
            self.edge_count,
            self.resample,
        )


@dataclasses.dataclass(frozen=True)
class TriangleEstimate:
    """(sourceSubtask, edgeCount, beta) — util/TriangleEstimate.java:23."""

    source_subtask: int
    edge_count: int
    beta: int

    def as_tuple(self) -> Tuple:
        return (self.source_subtask, self.edge_count, self.beta)

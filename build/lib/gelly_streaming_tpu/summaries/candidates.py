"""Bipartiteness summary view with the reference's Candidates rendering.

Reference: summaries/Candidates.java — ``(Boolean, TreeMap<componentId,
Map<vertexId, SignedVertex>>)`` (:27) built edge-by-edge with sign-conflict
detection (:61-74) and pairwise merge-with-parity (:77-192); any conflict
collapses to the global fail sentinel ``(false,{})`` (:194-196).

The TPU-native summary is the doubled-vertex parity union-find
(ops/unionfind.py): node 2v = "v side A", 2v+1 = "v side B"; an odd cycle
collapses a vertex's two sides into one component.  This class is the host view
that renders that array state in Candidates' exact toString format, e.g.
``(true,{1={1=(1,true), 2=(2,false)}})`` — component ids are the component's
minimum vertex; a sign is true iff the vertex lies on the same side as that
minimum vertex (matching the reference's min-endpoint-positive convention,
BipartitenessCheck.java:52-59).
"""

from __future__ import annotations

from typing import Dict, Tuple

import jax.numpy as jnp
import numpy as np

from gelly_streaming_tpu.ops import unionfind as uf


class Candidates:
    def __init__(self, parent2, seen):
        self.parent2 = parent2  # int32[2C] doubled-space union-find
        self.seen = seen  # bool[C]

    @property
    def capacity(self) -> int:
        return int(self.parent2.shape[0]) // 2

    def is_bipartite(self) -> bool:
        return bool(uf.is_bipartite(self.parent2, self.seen))

    def components(self) -> Dict[int, Dict[int, Tuple[int, bool]]]:
        """component-min-vertex -> {vertex -> (vertex, same_side_as_min)}."""
        p = np.asarray(uf.compress(self.parent2))
        seen = np.nonzero(np.asarray(self.seen))[0]
        even = p[2 * seen]
        odd = p[2 * seen + 1]
        comp_key = np.minimum(even, odd)
        comps: Dict[int, Dict[int, Tuple[int, bool]]] = {}
        for key in np.unique(comp_key):
            members = seen[comp_key == key]
            m = int(members.min())
            m_side = p[2 * m]
            entry = {}
            for v in members:
                entry[int(v)] = (int(v), bool(p[2 * v] == m_side))
            comps[m] = entry
        return comps

    def __str__(self) -> str:
        if not self.is_bipartite():
            return "(false,{})"
        comps = self.components()
        comp_strs = []
        for key in sorted(comps):
            inner = ", ".join(
                f"{v}=({v},{'true' if side else 'false'})"
                for v, (_, side) in sorted(comps[key].items())
            )
            comp_strs.append(f"{key}={{{inner}}}")
        return "(true,{" + ", ".join(comp_strs) + "})"
